"""Session: the per-cycle scheduling context.

Mirrors pkg/scheduler/framework/session.go + session_plugins.go: a deep-copy
snapshot of the cluster, 22 plugin extension-point registries with tiered
dispatch (first-tier-with-an-opinion for order fns, AND/intersection for
predicates and victim sets, Permit/Abstain/Reject voting for pipelined/
enqueueable), and the Allocate/Pipeline/Evict primitives that mutate session
state and dispatch to the cache when a gang becomes ready.

The TPU-specific addition is ``ssn.solver`` (framework/solver.py): the
batched task x node evaluation context that builtin plugins feed masks and
score terms into, replacing per-task goroutine fan-out with jitted kernels.
"""

from __future__ import annotations

import logging
import uuid
from typing import Callable, Dict, List, Optional

from ..models.cluster_info import ClusterInfo
from ..models.job_info import JobInfo, TaskInfo, TaskStatus
from ..models.node_info import NodeInfo
from ..models.queue_info import NamespaceInfo, QueueInfo
from ..models.resource import Resource
from ..utils.clock import GLOBAL_CLOCK

# plugin voting values (reference: plugins/util/util.go:31-36)
PERMIT = 1
ABSTAIN = 0
REJECT = -1


class ValidateResult:
    def __init__(self, passed: bool, reason: str = "", message: str = ""):
        self.passed = passed
        self.reason = reason
        self.message = message


class Event:
    def __init__(self, task: TaskInfo):
        self.task = task


class EventHandler:
    """Per-task Allocate/Deallocate hooks, with optional batched forms.

    ``batch_allocate_func(job, tasks, total_resource)`` lets additive
    plugins (drf, proportion) absorb a whole gang's placement in one call
    instead of one share recompute per task; handlers without a batch form
    are fed per-task events by the session's batched fire, so semantics
    are identical either way."""

    def __init__(self, allocate_func: Optional[Callable] = None,
                 deallocate_func: Optional[Callable] = None,
                 batch_allocate_func: Optional[Callable] = None,
                 batch_deallocate_func: Optional[Callable] = None):
        self.allocate_func = allocate_func
        self.deallocate_func = deallocate_func
        self.batch_allocate_func = batch_allocate_func
        self.batch_deallocate_func = batch_deallocate_func


_FN_MAPS = (
    "job_order_fns", "queue_order_fns", "task_order_fns", "namespace_order_fns",
    "cluster_order_fns", "predicate_fns", "best_node_fns", "node_order_fns",
    "batch_node_order_fns", "node_map_fns", "node_reduce_fns",
    "preemptable_fns", "reclaimable_fns", "overused_fns", "job_ready_fns",
    "job_pipelined_fns", "job_valid_fns", "job_enqueueable_fns",
    "job_enqueued_fns", "target_job_fns", "reserved_nodes_fns",
    "victim_tasks_fns", "job_starving_fns",
)

# extension-point -> conf enable flag consulted during dispatch
_ENABLE_FOR = {
    "job_order_fns": "enabledJobOrder",
    "namespace_order_fns": "enabledNamespaceOrder",
    "queue_order_fns": "enabledQueueOrder",
    "task_order_fns": "enabledTaskOrder",
    "predicate_fns": "enabledPredicate",
    "best_node_fns": "enabledBestNode",
    "node_order_fns": "enabledNodeOrder",
    "batch_node_order_fns": "enabledNodeOrder",
    "node_map_fns": "enabledNodeOrder",
    "node_reduce_fns": "enabledNodeOrder",
    "preemptable_fns": "enabledPreemptable",
    "reclaimable_fns": "enabledReclaimable",
    "overused_fns": "enabledOverused",
    "job_ready_fns": "enabledJobReady",
    "job_pipelined_fns": "enabledJobPipelined",
    "job_valid_fns": None,
    "job_enqueueable_fns": "enabledJobEnqueued",
    "job_enqueued_fns": "enabledJobEnqueued",
    "target_job_fns": "enabledTargetJob",
    "reserved_nodes_fns": "enabledReservedNodes",
    "victim_tasks_fns": "enabledVictim",
    "job_starving_fns": "enabledJobStarving",
}


_session_log = logging.getLogger(__name__)


class Session:
    """One scheduling cycle's context."""

    def __init__(self, cache, snapshot: ClusterInfo, tiers,
                 configurations=None, clock=None):
        self.uid = str(uuid.uuid4())
        self.cache = cache
        self.kube_client = cache.client() if cache is not None else None
        # time-dependent plugins (sla, ...) must read this, never
        # time.time(): wall time in production, virtual under the churn
        # simulator, so decisions compare against the same timebase that
        # stamped creation_timestamp. An explicit clock (Scheduler's)
        # wins; otherwise the store's clock is the source of truth.
        self.clock = clock if clock is not None else \
            (getattr(self.kube_client, "clock", None) or GLOBAL_CLOCK)
        self.jobs: Dict[str, JobInfo] = snapshot.jobs
        self.nodes: Dict[str, NodeInfo] = snapshot.nodes
        self.queues: Dict[str, QueueInfo] = snapshot.queues
        self.namespace_info: Dict[str, NamespaceInfo] = snapshot.namespaces
        self.revocable_nodes: Dict[str, NodeInfo] = snapshot.revocable_nodes
        self.node_list: List[NodeInfo] = [self.nodes[n] for n in snapshot.node_list
                                          if n in self.nodes]
        self.tiers = tiers
        self.configurations = configurations or {}
        self.plugins: Dict[str, object] = {}
        self.event_handlers: List[EventHandler] = []
        for m in _FN_MAPS:
            setattr(self, m, {})
        self._enabled_fns_cache: Dict[str, list] = {}
        self._victims_chain_cache: Dict[str, list] = {}
        # TPU batch solver context, populated by open_session
        self.solver = None
        # deferred-apply queue: gangs whose object-model staging (status
        # moves, node accounting, pod spec writes) is postponed until
        # something actually reads session placement state — see
        # materialize(). Readiness/rollups stay exact via the per-job
        # deferred_alloc/deferred_pipe deltas.
        # insertion-ordered uid -> [ops]: materialize() walks all values,
        # materialize_job() pops one key in O(1)
        self._deferred_ops: Dict[str, List[object]] = {}
        # incremental steady-state cycle (docs/design/incremental_cycle.md):
        # jobs/nodes THIS session mutated. The persistent snapshot hands
        # the same objects to the next session, so close_session feeds
        # these back into the cache's dirty sets — every touched entity is
        # re-cloned from cache truth before it is read again. Populated by
        # the session/statement primitives (the only sanctioned mutation
        # funnels) plus the podgroup condition/status writers.
        self.touched_jobs: set = set()
        self.touched_nodes: set = set()
        # incremental surface stamped by open_session (None = legacy full)
        self.incr_mode = None
        self.incr_seq = 0
        self.patched_jobs = None
        self.patched_nodes = None
        self.quiet_cycle = False

    def touch_job(self, uid: str) -> None:
        self.touched_jobs.add(uid)

    def touch_node(self, name: str) -> None:
        self.touched_nodes.add(name)

    # ------------------------------------------------------------------
    # deferred apply (allocate's burst-cycle fast path)
    # ------------------------------------------------------------------

    def defer_apply(self, op) -> None:
        """Queue a staged gang (a Statement _BatchOperation with
        ``applied=False``) for lazy object-model application."""
        self._deferred_ops.setdefault(op.job.uid, []).append(op)

    def _apply_deferred(self, op) -> None:
        try:
            op.apply(self)
        except Exception:
            # the kernel validated these fits against this same snapshot, so
            # an apply failure means internal drift. apply() rolled its
            # partial work back; continuing with only the delta accounting
            # would split state for the rest of the cycle (node accounting
            # missing the gang while readiness rollups count it, so
            # backfill/preempt could over-place against those nodes).
            if op.committed:
                # the gang's binds were already dispatched to the cache:
                # the pods are really binding, so the deltas must stand
                # (rollups stay exact); the cycle ends with optimistic node
                # accounting and the cache reconverges from the store
                _session_log.exception(
                    "deferred apply failed for job %s AFTER its binds were "
                    "dispatched; keeping delta-based accounting", op.job.uid)
            else:
                # not committed yet: drop the gang entirely — reverse the
                # deltas, clear the node_name markers, fire the deallocate
                # events, and mark the op dead so its statement's commit
                # skips the bind and discard skips the un-stage
                _session_log.exception(
                    "deferred apply failed for job %s; dropping the gang "
                    "(it re-enters as Pending next cycle)", op.job.uid)
                op.drop(self)
                op.dead = True

    def materialize(self) -> None:
        """Apply every pending deferred gang to the session's object model
        (in staging order). Called by anything that reads placement state:
        solver context builds, later actions, gang's unready reporting.
        No-op when nothing is deferred."""
        if not self._deferred_ops:
            return
        by_job, self._deferred_ops = self._deferred_ops, {}
        for ops in by_job.values():
            for op in ops:
                self._apply_deferred(op)

    def materialize_job(self, job) -> None:
        """Materialize only the deferred gangs of one job (gang's
        unready-condition reporting touches single jobs)."""
        for op in self._deferred_ops.pop(job.uid, ()):
            self._apply_deferred(op)

    # ------------------------------------------------------------------
    # registration (AddXxxFn, session_plugins.go:37-140)
    # ------------------------------------------------------------------

    def _add(self, map_name: str, plugin_name: str, fn) -> None:
        getattr(self, map_name)[plugin_name] = fn
        self._enabled_fns_cache.pop(map_name, None)
        self._victims_chain_cache.pop(map_name, None)

    def add_job_order_fn(self, name, fn): self._add("job_order_fns", name, fn)
    def add_queue_order_fn(self, name, fn): self._add("queue_order_fns", name, fn)
    def add_task_order_fn(self, name, fn): self._add("task_order_fns", name, fn)
    def add_namespace_order_fn(self, name, fn): self._add("namespace_order_fns", name, fn)
    def add_predicate_fn(self, name, fn): self._add("predicate_fns", name, fn)
    def add_best_node_fn(self, name, fn): self._add("best_node_fns", name, fn)
    def add_node_order_fn(self, name, fn): self._add("node_order_fns", name, fn)
    def add_batch_node_order_fn(self, name, fn): self._add("batch_node_order_fns", name, fn)
    def add_node_map_fn(self, name, fn): self._add("node_map_fns", name, fn)
    def add_node_reduce_fn(self, name, fn): self._add("node_reduce_fns", name, fn)
    def add_preemptable_fn(self, name, fn): self._add("preemptable_fns", name, fn)
    def add_reclaimable_fn(self, name, fn): self._add("reclaimable_fns", name, fn)
    def add_overused_fn(self, name, fn): self._add("overused_fns", name, fn)
    def add_job_ready_fn(self, name, fn): self._add("job_ready_fns", name, fn)
    def add_job_pipelined_fn(self, name, fn): self._add("job_pipelined_fns", name, fn)
    def add_job_valid_fn(self, name, fn): self._add("job_valid_fns", name, fn)
    def add_job_enqueueable_fn(self, name, fn): self._add("job_enqueueable_fns", name, fn)
    def add_job_enqueued_fn(self, name, fn): self._add("job_enqueued_fns", name, fn)
    def add_target_job_fn(self, name, fn): self._add("target_job_fns", name, fn)
    def add_reserved_nodes_fn(self, name, fn): self._add("reserved_nodes_fns", name, fn)
    def add_victim_tasks_fns(self, name, fn): self._add("victim_tasks_fns", name, fn)
    def add_job_starving_fns(self, name, fn): self._add("job_starving_fns", name, fn)
    def add_event_handler(self, handler: EventHandler): self.event_handlers.append(handler)

    # ------------------------------------------------------------------
    # tiered dispatch
    # ------------------------------------------------------------------

    def plugin_enabled(self, plugin_name: str, flag: str) -> bool:
        """Whether the conf enables ``flag`` for ``plugin_name`` (unset flags
        default to enabled). Consulted by plugins before feeding the solver so
        the vectorized path honors per-extension-point enables exactly like
        tiered dispatch does for host fns."""
        for tier in self.tiers:
            for opt in tier.plugins:
                if opt.name == plugin_name:
                    return opt.is_enabled(flag)
        return True

    def _enabled_fns(self, map_name: str):
        """(tier_index, plugin_option, fn) honoring enable flags. Memoized:
        tiers and fn registrations are fixed after OnSessionOpen, and this
        resolution sits under every order-fn comparison on the hot path."""
        cached = self._enabled_fns_cache.get(map_name)
        if cached is not None:
            return cached
        fns = getattr(self, map_name)
        flag = _ENABLE_FOR.get(map_name)
        out = []
        for ti, tier in enumerate(self.tiers):
            for opt in tier.plugins:
                if flag is not None and not opt.is_enabled(flag):
                    continue
                fn = fns.get(opt.name)
                if fn is not None:
                    out.append((ti, opt, fn))
        self._enabled_fns_cache[map_name] = out
        return out

    def _compare_dispatch(self, map_name: str, l, r) -> Optional[int]:
        """First plugin with a non-zero comparison wins."""
        for _, _, fn in self._enabled_fns(map_name):
            v = fn(l, r)
            if v != 0:
                return v
        return None

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """Whether l should be placed before r (session_plugins.go:486-510);
        falls back to creation time then UID."""
        v = self._compare_dispatch("job_order_fns", l, r)
        if v is not None:
            return v < 0
        if l.creation_timestamp != r.creation_timestamp:
            return l.creation_timestamp < r.creation_timestamp
        return l.uid < r.uid

    def namespace_order_fn(self, l, r) -> bool:
        v = self._compare_dispatch("namespace_order_fns", l, r)
        if v is not None:
            return v < 0
        return l < r

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        v = self._compare_dispatch("queue_order_fns", l, r)
        if v is not None:
            return v < 0
        if l.queue.metadata.creation_timestamp != r.queue.metadata.creation_timestamp:
            return (l.queue.metadata.creation_timestamp
                    < r.queue.metadata.creation_timestamp)
        return l.uid < r.uid

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> Optional[int]:
        return self._compare_dispatch("task_order_fns", l, r)

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        v = self.task_compare_fns(l, r)
        if v is not None:
            return v < 0
        if l.priority != r.priority:
            return l.priority > r.priority
        return l.uid < r.uid

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """All enabled predicates must pass; raises FitError-carrying
        exceptions on failure (session_plugins.go:625-640)."""
        for _, _, fn in self._enabled_fns("predicate_fns"):
            fn(task, node)

    def best_node_fn(self, task: TaskInfo, node_scores) -> Optional[NodeInfo]:
        for _, _, fn in self._enabled_fns("best_node_fns"):
            best = fn(task, node_scores)
            if best is not None:
                return best
        return None

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        score = 0.0
        for _, _, fn in self._enabled_fns("node_order_fns"):
            score += fn(task, node)
        return score

    def batch_node_order_fn(self, task: TaskInfo, nodes) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for _, _, fn in self._enabled_fns("batch_node_order_fns"):
            for name, s in fn(task, nodes).items():
                total[name] = total.get(name, 0.0) + s
        return total

    def _victims_dispatch(self, map_name, claimer, claimees):
        """Per-tier intersection of victim sets (session_plugins.go:142-238):
        abstaining plugins skip; an empty candidate set (or an empty
        intersection) vetoes the tier and dispatch falls through to the next
        tier; the first tier producing a non-empty set decides."""
        chain = self._victims_chain_cache.get(map_name)
        if chain is None:
            # [(tier_index, [fn, ...])] — resolved once; fn maps are fixed
            # after OnSessionOpen (same contract as _enabled_fns)
            by_tier: Dict[int, list] = {}
            for ti, _, fn in self._enabled_fns(map_name):
                by_tier.setdefault(ti, []).append(fn)
            chain = sorted(by_tier.items())
            self._victims_chain_cache[map_name] = chain
        for ti, fns in chain:
            victims: Optional[list] = None
            for fn in fns:
                candidates, abstain = fn(claimer, claimees)
                if abstain == ABSTAIN:
                    continue
                if not candidates:
                    victims = None
                    break
                if victims is None:
                    victims = list(candidates)
                else:
                    cand_ids = {c.uid for c in candidates}
                    victims = [v for v in victims if v.uid in cand_ids]
                    if not victims:
                        victims = None
                        break
            if victims:
                return victims
        return []

    def preemptable(self, preemptor: TaskInfo, preemptees) -> list:
        return self._victims_dispatch("preemptable_fns", preemptor, preemptees)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees) -> list:
        return self._victims_dispatch("reclaimable_fns", reclaimer, reclaimees)

    def victim_tasks(self) -> list:
        """Union of all victim-task sets (session_plugins.go:427-450)."""
        victims = []
        seen = set()
        for _, _, fn in self._enabled_fns("victim_tasks_fns"):
            for v in fn():
                if v.uid not in seen:
                    seen.add(v.uid)
                    victims.append(v)
        return victims

    def overused(self, queue: QueueInfo) -> bool:
        for _, _, fn in self._enabled_fns("overused_fns"):
            if fn(queue):
                return True
        return False

    def job_ready(self, job: JobInfo) -> bool:
        for _, _, fn in self._enabled_fns("job_ready_fns"):
            if not fn(job):
                return False
        return True

    def _voting_dispatch(self, map_name: str, obj, default: bool) -> bool:
        """Permit/Abstain/Reject per tier (session_plugins.go:283-313)."""
        for ti, tier in enumerate(self.tiers):
            has_found = False
            flag = _ENABLE_FOR[map_name]
            fns = getattr(self, map_name)
            for opt in tier.plugins:
                if not opt.is_enabled(flag):
                    continue
                fn = fns.get(opt.name)
                if fn is None:
                    continue
                res = fn(obj)
                if res < 0:
                    return False
                if res > 0:
                    has_found = True
            if has_found:
                return True
        return default

    def job_pipelined(self, job: JobInfo) -> bool:
        return self._voting_dispatch("job_pipelined_fns", job, True)

    def job_enqueueable(self, job: JobInfo) -> bool:
        return self._voting_dispatch("job_enqueueable_fns", job, True)

    def job_enqueued(self, job: JobInfo) -> None:
        for _, _, fn in self._enabled_fns("job_enqueued_fns"):
            fn(job)

    def job_starving(self, job: JobInfo) -> bool:
        """AND within the first tier that registered (session_plugins.go:
        315-340)."""
        for ti, tier in enumerate(self.tiers):
            has_found = False
            fns = self.job_starving_fns
            for opt in tier.plugins:
                if not opt.is_enabled("enabledJobStarving"):
                    continue
                fn = fns.get(opt.name)
                if fn is None:
                    continue
                has_found = True
                if not fn(job):
                    return False
            if has_found:
                return True
        return False

    def job_valid(self, job: JobInfo) -> Optional[ValidateResult]:
        for _, _, fn in self._enabled_fns("job_valid_fns"):
            vr = fn(job)
            if vr is not None and not vr.passed:
                return vr
        return None

    def target_job(self, jobs) -> Optional[JobInfo]:
        for _, _, fn in self._enabled_fns("target_job_fns"):
            target = fn(jobs)
            if target is not None:
                return target
        return None

    def reserved_nodes(self) -> None:
        for _, _, fn in self._enabled_fns("reserved_nodes_fns"):
            fn()

    # ------------------------------------------------------------------
    # primitives (session.go:238-345)
    # ------------------------------------------------------------------

    def statement(self):
        from .statement import Statement
        return Statement(self)

    def _fire_allocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def _fire_deallocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))

    def _fire_allocate_batch(self, job, tasks, total=None) -> None:
        """One event round for a whole gang's placements. ``total`` may be
        passed by callers that already hold the gang's resource sum."""
        if not tasks:
            return
        if total is None:
            total = Resource()
            for t in tasks:
                total.add(t.resreq)
        for eh in self.event_handlers:
            if eh.batch_allocate_func is not None:
                eh.batch_allocate_func(job, tasks, total)
            elif eh.allocate_func is not None:
                for t in tasks:
                    eh.allocate_func(Event(t))

    def _fire_deallocate_batch(self, job, tasks) -> None:
        if not tasks:
            return
        total = Resource()
        for t in tasks:
            total.add(t.resreq)
        for eh in self.event_handlers:
            if eh.batch_deallocate_func is not None:
                eh.batch_deallocate_func(job, tasks, total)
            elif eh.deallocate_func is not None:
                for t in tasks:
                    eh.deallocate_func(Event(t))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Assign onto releasing resources; session-state only."""
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        job.update_task_status(task, TaskStatus.Pipelined)
        task.node_name = hostname
        node.add_task(task)
        self.touched_jobs.add(task.job)
        self.touched_nodes.add(hostname)
        self._fire_allocate(task)

    def allocate(self, task: TaskInfo, node_info: NodeInfo) -> None:
        """Assign onto idle resources; dispatches the whole gang to the cache
        binder once the job is ready (session.go:281-331)."""
        hostname = node_info.name
        pod_volumes = self.cache.volume_binder.get_pod_volumes(task, node_info.node) \
            if self.cache is not None else None
        if self.cache is not None:
            self.cache.volume_binder.allocate_volumes(task, hostname, pod_volumes)
        task.pod_volumes = pod_volumes
        task.pod.spec.node_name = hostname
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        job.update_task_status(task, TaskStatus.Allocated)
        task.node_name = hostname
        node.add_task(task)
        self.touched_jobs.add(task.job)
        self.touched_nodes.add(hostname)
        self._fire_allocate(task)
        if self.job_ready(job):
            for t in list(job.task_status_index.get(TaskStatus.Allocated, {}).values()):
                self.dispatch(t, t.pod_volumes)

    def dispatch(self, task: TaskInfo, volumes=None) -> None:
        """Send a session-allocated task to the cache for real binding."""
        if self.cache is not None:
            self.cache.volume_binder.bind_volumes(task, volumes
                                                  if volumes is not None
                                                  else task.pod_volumes)
            self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.Binding)
            self.touched_jobs.add(task.job)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Immediate eviction (used by reclaim): session state + cache."""
        job = self.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        node = self.nodes.get(reclaimee.node_name)
        if node is None:
            raise KeyError(f"failed to find node {reclaimee.node_name}")
        job.update_task_status(reclaimee, TaskStatus.Releasing)
        node.update_task(reclaimee)
        self.touched_jobs.add(reclaimee.job)
        self.touched_nodes.add(reclaimee.node_name)
        self._fire_deallocate(reclaimee)
        if self.cache is not None:
            self.cache.evict(reclaimee, reason)

    def __repr__(self):
        return (f"Session {self.uid}: jobs={len(self.jobs)} "
                f"nodes={len(self.nodes)} queues={len(self.queues)}")
