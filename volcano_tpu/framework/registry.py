"""Plugin and action registries (reference: pkg/scheduler/framework/
plugins.go:37-119 + actions/factory.go).

Out-of-tree plugins load through Python entry points in the
``volcano_tpu.plugins`` group -- the TPU-native analogue of the reference's
dynamic ``.so`` loading via plugin.Open/Lookup("New")
(plugins.go:62-101 LoadCustomPlugins).
"""

from __future__ import annotations

import importlib.metadata
from typing import Callable, Dict, Optional

PluginBuilder = Callable  # (Arguments) -> Plugin

_plugin_builders: Dict[str, PluginBuilder] = {}
_actions: Dict[str, object] = {}


def register_plugin_builder(name: str, builder: PluginBuilder) -> None:
    _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[PluginBuilder]:
    _ensure_builtins()
    if name not in _plugin_builders:
        load_custom_plugins()
    return _plugin_builders.get(name)


def register_action(action) -> None:
    _actions[action.name()] = action


def get_action(name: str) -> Optional[object]:
    _ensure_builtins()
    return _actions.get(name)


def load_plugins_dir(plugins_dir: str) -> list:
    """Load every *.py file in ``plugins_dir`` as a plugin module exposing
    ``New(arguments) -> Plugin`` (and optionally ``Name() -> str``) — the
    --plugins-dir flag equivalent of the reference's plugin.Open +
    Lookup("New") over .so files (framework/plugins.go:62-101).

    Returns the list of plugin names registered."""
    import importlib.util
    import os
    loaded = []
    if not plugins_dir or not os.path.isdir(plugins_dir):
        return loaded
    for fname in sorted(os.listdir(plugins_dir)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        path = os.path.join(plugins_dir, fname)
        mod_name = f"volcano_tpu_custom_{fname[:-3]}"
        try:
            spec = importlib.util.spec_from_file_location(mod_name, path)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
            new = getattr(module, "New", None)
            if new is None:
                continue
            name = module.Name() if hasattr(module, "Name") else fname[:-3]
            register_plugin_builder(name, new)
            loaded.append(name)
        except Exception:
            continue
    return loaded


def load_custom_plugins(group: str = "volcano_tpu.plugins") -> None:
    """Discover out-of-tree plugin builders via entry points."""
    try:
        eps = importlib.metadata.entry_points(group=group)
    except Exception:
        return
    for ep in eps:
        if ep.name not in _plugin_builders:
            try:
                _plugin_builders[ep.name] = ep.load()
            except Exception:
                continue


_builtins_loaded = False


def _ensure_builtins() -> None:
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from .. import actions as _actions_pkg   # noqa: F401 (registers via import)
    from .. import plugins as _plugins_pkg   # noqa: F401
