"""Plugin and action registries (reference: pkg/scheduler/framework/
plugins.go:37-119 + actions/factory.go).

Out-of-tree plugins load through Python entry points in the
``volcano_tpu.plugins`` group -- the TPU-native analogue of the reference's
dynamic ``.so`` loading via plugin.Open/Lookup("New")
(plugins.go:62-101 LoadCustomPlugins).
"""

from __future__ import annotations

import importlib.metadata
from typing import Callable, Dict, Optional

PluginBuilder = Callable  # (Arguments) -> Plugin

_plugin_builders: Dict[str, PluginBuilder] = {}
_actions: Dict[str, object] = {}


def register_plugin_builder(name: str, builder: PluginBuilder) -> None:
    _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[PluginBuilder]:
    _ensure_builtins()
    if name not in _plugin_builders:
        load_custom_plugins()
    return _plugin_builders.get(name)


def register_action(action) -> None:
    _actions[action.name()] = action


def get_action(name: str) -> Optional[object]:
    _ensure_builtins()
    return _actions.get(name)


def load_custom_plugins(group: str = "volcano_tpu.plugins") -> None:
    """Discover out-of-tree plugin builders via entry points."""
    try:
        eps = importlib.metadata.entry_points(group=group)
    except Exception:
        return
    for ep in eps:
        if ep.name not in _plugin_builders:
            try:
                _plugin_builders[ep.name] = ep.load()
            except Exception:
                continue


_builtins_loaded = False


def _ensure_builtins() -> None:
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from .. import actions as _actions_pkg   # noqa: F401 (registers via import)
    from .. import plugins as _plugins_pkg   # noqa: F401
