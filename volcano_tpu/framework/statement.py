"""Statement: the transactional operation log enabling gang all-or-nothing
(reference: pkg/scheduler/framework/statement.go).

Evict/Pipeline/Allocate are staged against session state only; Commit
replays them against the cache (real binds/evictions), Discard rolls them
back in reverse order (statement.go:350-393).
"""

from __future__ import annotations

from typing import List, Optional

from ..models.job_info import TaskInfo, TaskStatus


class _Operation:
    def __init__(self, name: str, task: TaskInfo, reason: str = ""):
        self.name = name
        self.task = task
        self.reason = reason


class _BatchOperation:
    """One staged gang: [(task, node_info, pipelined)] applied together."""

    name = "batch"
    applied = True
    # set by Session._apply_deferred when a deferred apply failed and the
    # gang was dropped: commit must not bind it, discard must not un-stage it
    dead = False
    # flipped by _commit_batch once the gang's binds were dispatched to the
    # cache: a later deferred-apply failure must NOT drop the gang then —
    # the pods are really binding, so the delta accounting has to stand
    committed = False

    def __init__(self, job, items):
        self.job = job
        self.items = items


class _DeferredBatch(_BatchOperation):
    """A staged gang whose object-model apply is deferred
    (Session.materialize). Until ``apply`` runs, the placements exist as
    task.node_name strings plus the job's deferred_alloc/deferred_pipe
    deltas; statuses stay Pending and node accounting is untouched."""

    applied = False

    def apply(self, ssn) -> None:
        """The postponed staging: bulk status moves, per-node bulk
        accounting, pod spec writes. All-or-nothing: on any failure the
        partial mutations are undone, the deltas stay in force (rollups
        remain exact for the committed gang) and the error re-raises;
        ``applied``/delta bookkeeping only flips after full success.

        KEEP IN SYNC with AllocateAction._stage_bulk's eager branch: that
        path stages the same mutations cross-job (with per-node group
        totals and per-job failure routing); this one applies a single
        already-validated gang."""
        if self.applied:
            return
        job = self.job
        alloc = [t for t, _, p in self.items if not p]
        pipe = [t for t, _, p in self.items if p]
        moved: List = []
        added: List = []
        try:
            if alloc:
                job.move_tasks_status_bulk(alloc, TaskStatus.Allocated)
                moved.append(alloc)
            if pipe:
                job.move_tasks_status_bulk(pipe, TaskStatus.Pipelined)
                moved.append(pipe)
            groups: dict = {}
            for task, node, pipelined in self.items:
                g = groups.setdefault((id(node), pipelined),
                                      (node, pipelined, []))
                g[2].append(task)
            for node, pipelined, tasks in groups.values():
                node.add_tasks_bulk(tasks, pipelined, share_objects=True)
                added.append((node, pipelined, tasks))
                if not pipelined:
                    name = node.name
                    for t in tasks:
                        t.pod.spec.node_name = name
        except BaseException:
            for node, pipelined, tasks in reversed(added):
                for t in tasks:
                    node.remove_task(t)
                    t.node_name = node.name   # keep the deferred marker
                    if not pipelined:
                        t.pod.spec.node_name = ""
            for tasks in reversed(moved):
                job.move_tasks_status_bulk(tasks, TaskStatus.Pending)
            raise
        self.applied = True
        job.deferred_alloc -= len(alloc)
        job.deferred_pipe -= len(pipe)

    def drop(self, ssn) -> None:
        """Discard before apply: reverse the deltas and the eager
        node_name/event effects; nothing else was mutated. Marks the op
        applied so a queued materialize skips it."""
        self.applied = True
        job = self.job
        alloc_n = sum(1 for _, _, p in self.items if not p)
        job.deferred_alloc -= alloc_n
        job.deferred_pipe -= len(self.items) - alloc_n
        for task, _, _ in self.items:
            task.node_name = ""
        ssn._fire_deallocate_batch(job, [t for t, _, _ in self.items])


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[_Operation] = []

    # -- evict (statement.go:61-134) --------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Stage an eviction: session state flips to Releasing now; the pod
        delete happens at Commit."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is None:
            raise KeyError(f"failed to find node {reclaimee.node_name}")
        job.move_task_status(reclaimee, TaskStatus.Releasing)
        node.transition_task(reclaimee)
        self.ssn.touched_jobs.add(reclaimee.job)
        self.ssn.touched_nodes.add(reclaimee.node_name)
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append(_Operation("evict", reclaimee, reason))

    def _unevict(self, reclaimee: TaskInfo) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if job is not None:
            job.move_task_status(reclaimee, TaskStatus.Running)
        if node is not None:
            node.transition_task(reclaimee)
        self.ssn._fire_allocate(reclaimee)

    # -- pipeline (statement.go:136-230) ----------------------------------

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        node = self.ssn.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        job.update_task_status(task, TaskStatus.Pipelined)
        task.node_name = hostname
        node.add_task(task)
        self.ssn.touched_jobs.add(task.job)
        self.ssn.touched_nodes.add(hostname)
        self.ssn._fire_allocate(task)
        self.operations.append(_Operation("pipeline", task))

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pending)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- allocate (statement.go:232-348) ----------------------------------

    def allocate(self, task: TaskInfo, node_info) -> None:
        hostname = node_info.name if hasattr(node_info, "name") else str(node_info)
        if self.ssn.cache is not None:
            pod_volumes = self.ssn.cache.volume_binder.get_pod_volumes(
                task, getattr(self.ssn.nodes.get(hostname), "node", None))
            self.ssn.cache.volume_binder.allocate_volumes(task, hostname, pod_volumes)
            task.pod_volumes = pod_volumes
        job = self.ssn.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        node = self.ssn.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        task.pod.spec.node_name = hostname
        job.update_task_status(task, TaskStatus.Allocated)
        task.node_name = hostname
        node.add_task(task)
        self.ssn.touched_jobs.add(task.job)
        self.ssn.touched_nodes.add(hostname)
        self.ssn._fire_allocate(task)
        self.operations.append(_Operation("allocate", task))

    def _unallocate(self, task: TaskInfo) -> None:
        if self.ssn.cache is not None and task.pod_volumes is not None:
            self.ssn.cache.volume_binder.release_volumes(task,
                                                         task.pod_volumes)
            task.pod_volumes = None
        job = self.ssn.jobs.get(task.job)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pending)
        task.node_name = ""
        task.pod.spec.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- batch allocate (the hot path's staging) ---------------------------

    def allocate_batch(self, job, placements, keep_partial: bool = False) -> None:
        """Stage a whole gang's placements: ``[(task, node_info,
        pipelined)]``.

        Semantically identical to calling :meth:`pipeline` /
        :meth:`allocate` once per task, but the plugin event round is
        batched (one share recompute per gang instead of per task —
        EventHandler.batch_allocate_func). Tasks whose pods mount volumes
        take the per-task path because volume planning can fail per task.

        On a failed placement: with ``keep_partial`` (best-effort surplus,
        the reference's break-on-first-failure loop) the already-staged
        prefix is kept; otherwise everything — including the failing
        task's partial mutations — is rolled back and the error re-raised."""
        ssn = self.ssn
        fast = []
        for task, node, pipelined in placements:
            if ssn.cache is not None and task.has_volumes:
                if pipelined:
                    self.pipeline(task, node.name)
                else:
                    self.allocate(task, node)
                continue
            fast.append((task, node, pipelined))
        if not fast:
            return

        applied = self._stage_fast_seq(fast, keep_partial)
        if applied:
            self._touch_items(job, applied)
            ssn._fire_allocate_batch(job, [t for t, _, _ in applied])
            self.operations.append(_BatchOperation(job, applied))

    def _touch_items(self, job, items) -> None:
        """Record a staged gang in the session's touched sets (the
        incremental snapshot's re-clone scope): the job plus every node
        the gang landed on. Rolled-back gangs stay marked — conservative
        re-clones are always sound."""
        ssn = self.ssn
        ssn.touched_jobs.add(job.uid)
        touched_nodes = ssn.touched_nodes
        for _, node, _ in items:
            touched_nodes.add(node.name)

    def _stage_fast_seq(self, fast, keep_partial: bool) -> list:
        """Sequential per-task staging: all-or-nothing by default, prefix
        (keep-partial) semantics on request. This is the fallback path —
        the allocate action's phase-level bulk apply
        (AllocateAction._stage_bulk) handles the hot case."""
        ssn = self.ssn

        def undo(task, node, pipelined, registered: bool) -> None:
            """Revert one staged placement (add_task itself is atomic on
            error, so an unregistered task never touched the node)."""
            if registered:
                node.remove_task(task)
            job_of = ssn.jobs.get(task.job)
            if job_of is not None and task.status != TaskStatus.Pending:
                job_of.move_task_status(task, TaskStatus.Pending)
            task.node_name = ""
            if not pipelined:
                task.pod.spec.node_name = ""

        applied = []
        failure: Optional[BaseException] = None
        for task, node, pipelined in fast:
            job_of = ssn.jobs.get(task.job)
            try:
                if job_of is None:
                    raise KeyError(f"failed to find job {task.job}")
                if pipelined:
                    job_of.move_task_status(task, TaskStatus.Pipelined)
                else:
                    task.pod.spec.node_name = node.name
                    job_of.move_task_status(task, TaskStatus.Allocated)
                task.node_name = node.name
                node.add_task(task)
            except Exception as e:
                undo(task, node, pipelined, registered=False)
                failure = e
                break
            applied.append((task, node, pipelined))
        if failure is not None and not keep_partial:
            for task, node, pipelined in reversed(applied):
                undo(task, node, pipelined, registered=True)
            raise failure
        return applied

    def record_batch(self, job, items, total=None) -> None:
        """Register an externally staged gang (the allocate action's
        phase-level bulk apply) for commit/discard: fires the batched
        plugin events and appends the operation, exactly like
        :meth:`allocate_batch` does after its own staging. ``total`` may
        carry the gang's precomputed resource sum."""
        self._touch_items(job, items)
        self.ssn._fire_allocate_batch(job, [t for t, _, _ in items], total)
        self.operations.append(_BatchOperation(job, items))

    def record_batch_deferred(self, job, items, total=None) -> None:
        """Register a gang with DEFERRED object-model staging: fires the
        batched plugin events now (handlers read task.resreq/node_name,
        both already set), bumps the job's readiness deltas, and queues
        the apply for Session.materialize."""
        op = _DeferredBatch(job, items)
        self._touch_items(job, items)
        alloc_n = sum(1 for _, _, p in items if not p)
        job.deferred_alloc += alloc_n
        job.deferred_pipe += len(items) - alloc_n
        self.ssn._fire_allocate_batch(job, [t for t, _, _ in items], total)
        self.ssn.defer_apply(op)
        self.operations.append(op)

    def _unbatch(self, op: _BatchOperation) -> None:
        for task, node, pipelined in reversed(op.items):
            node.remove_task(task)
            job_of = self.ssn.jobs.get(task.job)
            if job_of is not None:
                job_of.move_task_status(task, TaskStatus.Pending)
            task.node_name = ""
            if not pipelined:
                task.pod.spec.node_name = ""
        self.ssn._fire_deallocate_batch(op.job, [t for t, _, _ in op.items])

    def _commit_batch(self, op: _BatchOperation) -> None:
        """Dispatch a staged gang: allocated tasks bind through the cache
        in one locked pass (cache.bind_batch); pipelined ones stay
        session-state only, exactly like the per-task ops."""
        ssn = self.ssn
        if op.dead:
            return   # apply failed mid-cycle; the gang was dropped
        to_bind = [(task, node.name) for task, node, pipelined in op.items
                   if not pipelined]
        if not to_bind:
            return   # all-pipelined gang: nothing dispatched, drop stays safe
        if ssn.cache is not None:
            accepted = ssn.cache.bind_batch(to_bind)
        else:
            accepted = [t for t, _ in to_bind]
        if not accepted:
            return
        op.committed = True
        if not op.applied:
            return   # statuses still deferred; deltas carry the accounting
        job_of = ssn.jobs.get(op.job.uid)
        if job_of is not None and \
                all(t.job == op.job.uid for t in accepted):
            job_of.move_tasks_status_bulk(accepted, TaskStatus.Binding)
        else:   # mixed/foreign tasks: per-task fallback
            for task in accepted:
                job_t = ssn.jobs.get(task.job)
                if job_t is not None:
                    job_t.move_task_status(task, TaskStatus.Binding)

    # -- commit / discard (statement.go:350-393) ---------------------------

    def discard(self) -> None:
        """Roll back all staged operations in reverse order."""
        for op in reversed(self.operations):
            if op.name == "evict":
                self._unevict(op.task)
            elif op.name == "pipeline":
                self._unpipeline(op.task)
            elif op.name == "allocate":
                self._unallocate(op.task)
            elif op.name == "batch":
                if op.dead:
                    continue   # already dropped by Session._apply_deferred
                if op.applied:
                    self._unbatch(op)
                else:
                    # deferred and never materialized: reverse the deltas;
                    # drop() marks the op applied so the queued
                    # materialize entry becomes a no-op (no O(n) removal)
                    op.drop(self.ssn)
        self.operations = []

    def commit(self) -> None:
        """Replay staged operations against the cache. Consecutive evicts
        dispatch as one ``cache.evict_batch`` (one mutex pass + one executor
        submission; order within the statement is preserved)."""
        ops, self.operations = self.operations, []
        evicts: List[_Operation] = []

        def flush_evicts() -> None:
            if not evicts:
                return
            if self.ssn.cache is not None:
                self.ssn.cache.evict_batch(
                    [(e.task, e.reason) for e in evicts])
            evicts.clear()

        for op in ops:
            if op.name == "evict":
                if self.ssn.cache is not None:
                    evicts.append(op)
                continue
            if op.name == "pipeline":
                # session-state only until resources actually release — no
                # cache dispatch, so it needs no evict barrier (preempt
                # interleaves evict/pipeline per victim; flushing here
                # degraded the batched dispatch to one evict per call)
                continue
            flush_evicts()
            if op.name == "allocate":
                try:
                    self.ssn.dispatch(op.task, op.task.pod_volumes)
                except KeyError:
                    pass
            elif op.name == "batch":
                self._commit_batch(op)
        flush_evicts()
