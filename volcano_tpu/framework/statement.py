"""Statement: the transactional operation log enabling gang all-or-nothing
(reference: pkg/scheduler/framework/statement.go).

Evict/Pipeline/Allocate are staged against session state only; Commit
replays them against the cache (real binds/evictions), Discard rolls them
back in reverse order (statement.go:350-393).
"""

from __future__ import annotations

from typing import List, Optional

from ..models.job_info import TaskInfo, TaskStatus


class _Operation:
    def __init__(self, name: str, task: TaskInfo, reason: str = ""):
        self.name = name
        self.task = task
        self.reason = reason


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[_Operation] = []

    # -- evict (statement.go:61-134) --------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Stage an eviction: session state flips to Releasing now; the pod
        delete happens at Commit."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is None:
            raise KeyError(f"failed to find node {reclaimee.node_name}")
        job.update_task_status(reclaimee, TaskStatus.Releasing)
        node.update_task(reclaimee)
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append(_Operation("evict", reclaimee, reason))

    def _unevict(self, reclaimee: TaskInfo) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.Running)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_allocate(reclaimee)

    # -- pipeline (statement.go:136-230) ----------------------------------

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        node = self.ssn.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        job.update_task_status(task, TaskStatus.Pipelined)
        task.node_name = hostname
        node.add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(_Operation("pipeline", task))

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pending)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- allocate (statement.go:232-348) ----------------------------------

    def allocate(self, task: TaskInfo, node_info) -> None:
        hostname = node_info.name if hasattr(node_info, "name") else str(node_info)
        if self.ssn.cache is not None:
            pod_volumes = self.ssn.cache.volume_binder.get_pod_volumes(
                task, getattr(self.ssn.nodes.get(hostname), "node", None))
            self.ssn.cache.volume_binder.allocate_volumes(task, hostname, pod_volumes)
            task.pod_volumes = pod_volumes
        job = self.ssn.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        node = self.ssn.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        task.pod.spec.node_name = hostname
        job.update_task_status(task, TaskStatus.Allocated)
        task.node_name = hostname
        node.add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(_Operation("allocate", task))

    def _unallocate(self, task: TaskInfo) -> None:
        if self.ssn.cache is not None and task.pod_volumes is not None:
            self.ssn.cache.volume_binder.release_volumes(task,
                                                         task.pod_volumes)
            task.pod_volumes = None
        job = self.ssn.jobs.get(task.job)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pending)
        task.node_name = ""
        task.pod.spec.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- commit / discard (statement.go:350-393) ---------------------------

    def discard(self) -> None:
        """Roll back all staged operations in reverse order."""
        for op in reversed(self.operations):
            if op.name == "evict":
                self._unevict(op.task)
            elif op.name == "pipeline":
                self._unpipeline(op.task)
            elif op.name == "allocate":
                self._unallocate(op.task)
        self.operations = []

    def commit(self) -> None:
        """Replay staged operations against the cache."""
        ops, self.operations = self.operations, []
        for op in ops:
            if op.name == "evict":
                if self.ssn.cache is not None:
                    try:
                        self.ssn.cache.evict(op.task, op.reason)
                    except KeyError:
                        pass
            elif op.name == "pipeline":
                pass  # session-state only until resources actually release
            elif op.name == "allocate":
                try:
                    self.ssn.dispatch(op.task, op.task.pod_volumes)
                except KeyError:
                    pass
