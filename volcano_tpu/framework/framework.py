"""OpenSession / CloseSession (reference: pkg/scheduler/framework/
framework.go:30-58 + session.go:87-228 + job_updater.go).

Divergence from the reference, by design: job validation (JobValid) runs
*after* plugins' OnSessionOpen. The reference calls it before Tiers are even
assigned (framework.go:31-33 vs session.go:136), making it a no-op there;
running it after plugin registration realizes the documented intent (drop
invalid gangs and write the Unschedulable condition).
"""

from __future__ import annotations

import time as _time
from typing import Dict

from ..models.job_info import JobInfo, TaskStatus, allocated_status
from ..models.objects import (PodGroupCondition, PodGroupConditionType,
                              PodGroupPhase)
from ..models.resource import Resource
from .registry import get_plugin_builder
from .session import Session
from .solver import BatchSolver


# actions that are provably no-ops on a quiet cycle (no dirty state, no
# pending work): the quiet fast path below may only skip plugin opens
# when the conf runs nothing outside this set — elect/reserve make
# TIME-based reservation decisions that need live plugins every cycle
QUIET_SAFE_ACTIONS = frozenset(
    ("enqueue", "allocate", "backfill", "preempt", "reclaim"))


def open_session(cache, tiers, configurations=None, clock=None,
                 actions=None) -> Session:
    """Open one scheduling cycle's session.

    ``actions`` (the conf's action-name list) gates the incremental
    QUIET fast path: on a snapshot with nothing dirty and no pending
    work the plugin opens/JobValid sweep are provably decision-free, so
    they are skipped wholesale (docs/design/incremental_cycle.md) — but
    only when every configured action is quiet-safe. Callers that do not
    pass ``actions`` never take the fast path."""
    from ..trace import tracer as tr
    with tr.span("open_session"):
        with tr.span("snapshot"):
            snapshot = cache.snapshot()
        ssn = Session(cache, snapshot, tiers, configurations, clock=clock)
        ssn.solver = BatchSolver(ssn, rindex=snapshot.rindex)
        # incremental-cycle surface (consumed by the solver's persistent
        # device buffers, the allocate action's scoped working set and
        # the close-time writeback scope)
        ssn.incr_mode = snapshot.incr_mode
        ssn.incr_seq = snapshot.incr_seq
        ssn.patched_jobs = snapshot.patched_jobs
        ssn.patched_nodes = snapshot.patched_nodes
        ssn.quiet_cycle = bool(
            snapshot.quiet and actions is not None
            and QUIET_SAFE_ACTIONS.issuperset(actions))
        if snapshot.incr_mode is not None:
            from ..framework.solver import note_incremental_snapshot
            note_incremental_snapshot(cache, snapshot)
        # pre-session PodGroup statuses for jitter-deduped writeback:
        # maintained per patched job by the incremental snapshot, else
        # recomputed over every job like the reference
        if snapshot.pg_fprints is not None:
            ssn.pod_group_status = snapshot.pg_fprints
        else:
            ssn.pod_group_status: Dict[str, object] = {}
            for job in ssn.jobs.values():
                if job.pod_group is not None:
                    ssn.pod_group_status[job.uid] = _status_snapshot(
                        job.pod_group.status)
        if snapshot.total_resource is not None:
            ssn.total_resource = snapshot.total_resource
        else:
            ssn.total_resource = Resource()
            for n in ssn.nodes.values():
                ssn.total_resource.add(n.allocatable)

        # commit-path resilience (docs/design/resilience.md): pod keys
        # the cache has made ineligible for (re-)placement this cycle —
        # quarantined poison pods and bind-failure backoff windows. The
        # placing actions skip these tasks; why-pending reports the
        # reasons.
        ineligible = getattr(cache, "bind_ineligible", None)
        ssn.ineligible_binds = ineligible() if ineligible is not None \
            else {}

        if ssn.quiet_cycle:
            return ssn

        from ..metrics import metrics as m
        for tier in tiers:
            for opt in tier.plugins:
                builder = get_plugin_builder(opt.name)
                if builder is None:
                    continue
                plugin = builder(opt.arguments)
                ssn.plugins[plugin.name()] = plugin
                with m.plugin_timer(plugin.name(), "OnSessionOpen"), \
                        tr.span("plugin_open", plugin=plugin.name()):
                    plugin.on_session_open(ssn)

        # drop invalid gangs (JobValid), writing the Unschedulable
        # condition. Pending PodGroups are exempt: their pods don't exist
        # yet (the job controller gates pod creation on the enqueue action
        # moving the group to Inqueue), so gang's valid-task-count check
        # cannot apply to them.
        with tr.span("job_valid"):
            for job in list(ssn.jobs.values()):
                if job.pod_group is not None and \
                        job.pod_group.status.phase == PodGroupPhase.PENDING:
                    continue
                vr = ssn.job_valid(job)
                if vr is not None and not vr.passed:
                    update_pod_group_condition(ssn, job, PodGroupCondition(
                        type=PodGroupConditionType.UNSCHEDULABLE,
                        status="True", transition_id=ssn.uid,
                        reason=vr.reason, message=vr.message))
                    del ssn.jobs[job.uid]
        return ssn


def close_session(ssn: Session) -> None:
    from ..metrics import metrics as m
    from ..trace import tracer as tr
    with tr.span("close_session"):
        for plugin in ssn.plugins.values():
            with m.plugin_timer(plugin.name(), "OnSessionClose"), \
                    tr.span("plugin_close", plugin=plugin.name()):
                plugin.on_session_close(ssn)
        if tr.is_enabled():
            # "why pending" diagnosis for /debug/pending — after the
            # plugin closes (gang just wrote fit errors + conditions)
            from ..trace import pending as _pending
            with tr.span("pending_diagnosis"):
                _pending.publish(ssn)
        with tr.span("job_updater"):
            JobUpdater(ssn).update_all()
        ssn.plugins = {}
        ssn.event_handlers = []
        # incremental cycle: everything this session mutated must be
        # re-cloned from cache truth before the persistent snapshot is
        # read again (docs/design/incremental_cycle.md)
        if ssn.cache is not None and \
                getattr(ssn.cache, "incremental", False):
            ssn.cache.absorb_session_touches(ssn.touched_jobs,
                                             ssn.touched_nodes)


def update_pod_group_condition(ssn: Session, job: JobInfo,
                               condition: PodGroupCondition) -> None:
    """Replace an existing condition of the same type, else append
    (session.go:425-437 UpdatePodGroupCondition) -- conditions must not grow
    per cycle."""
    if job.pod_group is None:
        return
    condition.last_transition_time = _time.time()
    ssn.touched_jobs.add(job.uid)
    conditions = job.own_pod_group().status.conditions
    for i, c in enumerate(conditions):
        if c.type == condition.type:
            conditions[i] = condition
            return
    conditions.append(condition)


def job_status(ssn: Session, job: JobInfo):
    """Roll task counts into a PodGroup status (session.go:190-228).

    Copy-on-write aware: the candidate values are computed first and the
    (possibly shared) PodGroup is only claimed and mutated when something
    actually changed."""
    status = job.pod_group.status
    unschedulable = any(
        c.type == PodGroupConditionType.UNSCHEDULABLE and c.status == "True"
        and c.transition_id == ssn.uid
        for c in status.conditions)
    running = len(job.task_status_index.get(TaskStatus.Running, {}))
    phase = status.phase
    if running and unschedulable:
        phase = PodGroupPhase.UNKNOWN
    else:
        allocated = job.deferred_alloc
        for st, tasks in job.task_status_index.items():
            if allocated_status(st) or st == TaskStatus.Succeeded:
                allocated += len(tasks)
        if allocated >= job.pod_group.spec.min_member:
            phase = PodGroupPhase.RUNNING
        elif status.phase != PodGroupPhase.INQUEUE:
            phase = PodGroupPhase.PENDING
    failed = len(job.task_status_index.get(TaskStatus.Failed, {}))
    succeeded = len(job.task_status_index.get(TaskStatus.Succeeded, {}))
    if (phase, running, failed, succeeded) != \
            (status.phase, status.running, status.failed, status.succeeded):
        ssn.touched_jobs.add(job.uid)
        status = job.own_pod_group().status
        status.phase = phase
        status.running = running
        status.failed = failed
        status.succeeded = succeeded
    return status


def _status_snapshot(status) -> tuple:
    """Cheap immutable fingerprint of a PodGroup status for writeback
    dedup (replaces a deep clone per job per cycle). The incremental
    snapshot maintains the same fingerprints per patched job — one
    shared implementation (models.objects.status_fingerprint) so the two
    producers can never drift."""
    from ..models.objects import status_fingerprint
    return status_fingerprint(status)


# condition-writeback dedup window (job_updater.go:31-37)
JOB_CONDITION_UPDATE_TIME = 0.6
JOB_CONDITION_UPDATE_JITTER = 0.3


class JobUpdater:
    """Push changed PodGroup statuses back on session close
    (job_updater.go:40-108). The reference parallelizes over 16 goroutines;
    here the store write is an in-process call, so a plain loop is the
    faster equivalent."""

    def __init__(self, ssn: Session):
        self.ssn = ssn
        # incremental cycle: only patched (cache-side deltas) or touched
        # (session-side mutations) jobs can roll up differently from last
        # cycle's writeback — the sweep is scoped to them. Any job that
        # would need a FailedScheduling/condition write this cycle wrote
        # one LAST cycle too, whose echo dirtied it, so it is patched;
        # everything outside the scope provably pushes nothing.
        scope = None
        if getattr(ssn, "incr_mode", None) == "incremental":
            scope = set(ssn.patched_jobs or ()) | ssn.touched_jobs
        self.job_queue = [j for j in ssn.jobs.values()
                         if j.pod_group is not None
                         and (scope is None or j.uid in scope)]

    def update_all(self) -> None:
        """Compute statuses foreground, push the store writes on the cache
        executor — the reference parallelizes the API writes over 16
        goroutines (job_updater.go:51); with the GIL the equivalent is
        getting them off the cycle's critical path entirely (failures land
        in events/log, state reconverges via the watch echo)."""
        updates = []
        for job in self.job_queue:
            updates.append((job, self.prepare_job(job)))
        cache = self.ssn.cache
        if cache is None:
            return
        if updates:
            bulk = getattr(cache, "update_job_statuses", None)
            if bulk is not None:
                cache.submit_background(lambda: bulk(updates))
            else:
                cache.submit_background(
                    lambda: [cache.update_job_status(job, update_pg)
                             for job, update_pg in updates])

    def prepare_job(self, job: JobInfo) -> bool:
        """Roll up the job's status; True if the PodGroup must be pushed.

        No version-based skip here: task transitions arriving BETWEEN
        cycles leave the session-internal status version untouched while
        the stored PodGroup status is stale, so the rollup comparison
        itself is the only sound change check."""
        ssn = self.ssn
        status = job_status(ssn, job)
        old = getattr(ssn, "pod_group_status", {}).get(job.uid)
        return old is None or self._status_updated(status, old)

    @staticmethod
    def _status_updated(new, old: tuple) -> bool:
        """Compare a live status against its open-session fingerprint
        (_status_snapshot tuple)."""
        o_phase, o_running, o_succeeded, o_failed, o_conds = old
        if (new.phase, new.running, new.succeeded, new.failed) != \
                (o_phase, o_running, o_succeeded, o_failed):
            return True
        if len(new.conditions) != len(o_conds):
            return True
        for nc, (o_type, o_status, o_reason, o_message, o_ltt) in \
                zip(new.conditions, o_conds):
            # jitter dedup: a condition refreshed within the update window
            # counts as unchanged (TimeJitterAfter)
            if nc.last_transition_time - o_ltt > JOB_CONDITION_UPDATE_TIME:
                return True
            if (nc.type, nc.status, nc.reason, nc.message) != \
                    (o_type, o_status, o_reason, o_message):
                return True
        return False
