"""Data model: resources, API objects, scheduler info wrappers, snapshot arrays."""

from .resource import (EPS, INFINITY, ZERO, Resource, empty_resource,  # noqa: F401
                       min_resource)
from .objects import (Command, Job, JobAction, JobEvent, JobPhase, Node,  # noqa: F401
                      ObjectMeta, Pod, PodGroup, PodGroupPhase, PriorityClass,
                      Queue, QueueState)
from .job_info import (JobInfo, TaskInfo, TaskStatus, allocated_status,  # noqa: F401
                       get_job_id, get_task_id, get_task_status, is_terminated)
from .node_info import GPUDevice, NodeInfo  # noqa: F401
from .queue_info import NamespaceCollection, NamespaceInfo, QueueInfo  # noqa: F401
from .cluster_info import ClusterInfo  # noqa: F401
from .unschedule_info import FitError, FitErrors  # noqa: F401
