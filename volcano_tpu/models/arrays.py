"""Dense structure-of-arrays snapshot encoding for the TPU solver.

The reference evaluates predicates/scores task-by-task with goroutine fan-out
(pkg/scheduler/util/scheduler_helper.go:71-192). Here the per-cycle state is
encoded once into padded, statically-shaped arrays and every task x node
decision is computed by jitted kernels (volcano_tpu.ops).

Key encodings:

* **Resource index**: the cycle's resource dimensions [cpu, memory, *scalars]
  with per-dimension scale (memory is encoded in MiB to keep float32 exact)
  and the reference's 0.1 epsilon scaled alongside.
* **Task groups**: tasks sharing (job, task-spec, resreq, scheduling
  constraints) collapse into one group; predicates and static scores are
  evaluated per group x node, tasks index into their group. A 50k-task gang
  job costs as much mask memory as one task.
* **Feature matrices**: node labels/taints referenced by any group become
  integer-coded boolean matrices so selector/affinity/toleration matching is
  a matmul (MXU) instead of string comparisons.
* **Padding/bucketing**: node/task/group counts are padded to buckets so XLA
  recompiles only when a bucket boundary is crossed, with validity masks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import threading

import numpy as np

from .job_info import JobInfo, TaskInfo
from .node_info import NodeInfo
from .resource import CPU, EPS, MEMORY, Resource

MIB = float(2**20)

# scales: millicores stay, bytes -> MiB, scalar milli-units stay
def _scale_for(name: str) -> float:
    return 1.0 / MIB if name == MEMORY else 1.0


def bucket(n: int, size: int) -> int:
    """Round up to a bucket boundary (>= 1 bucket) for stable jit shapes."""
    return max(size, ((n + size - 1) // size) * size)


class ResourceIndex:
    """The cycle's resource-dimension registry."""

    def __init__(self, names: Sequence[str]):
        ordered = [CPU, MEMORY] + sorted(n for n in names if n not in (CPU, MEMORY))
        self.names: Tuple[str, ...] = tuple(ordered)
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}
        self.scales = np.array([_scale_for(n) for n in self.names], np.float32)
        self.eps = (EPS * self.scales).astype(np.float32)

    @property
    def r(self) -> int:
        return len(self.names)

    @classmethod
    def from_cluster(cls, nodes: Dict[str, NodeInfo],
                     jobs: Dict[str, JobInfo]) -> "ResourceIndex":
        names = set()
        for n in nodes.values():
            names.update(n.allocatable.scalars.keys())
        for j in jobs.values():
            names.update(j.total_request.scalars.keys())
        return cls(names)

    def vec(self, r: Resource) -> np.ndarray:
        v = np.zeros(self.r, np.float32)
        v[0] = r.milli_cpu
        v[1] = r.memory
        for name, quant in r.scalars.items():
            i = self.index.get(name)
            if i is not None:
                v[i] = quant
        return v * self.scales

    def resource(self, v: np.ndarray) -> Resource:
        """Inverse of :meth:`vec`: a Resource from a scaled row."""
        unscaled = np.asarray(v, np.float64) / self.scales
        r = Resource(milli_cpu=float(unscaled[0]), memory=float(unscaled[1]))
        for i in range(2, self.r):
            if unscaled[i]:
                r.set_scalar(self.names[i], float(unscaled[i]))
        return r

    def vec_capability(self, r: Resource) -> np.ndarray:
        """Capability-style vector: dimensions the resource does not mention
        are unbounded (the Infinity dimension default, resource_info.go:43)."""
        v = np.full(self.r, np.inf, np.float32)
        if r.milli_cpu > 0:
            v[0] = r.milli_cpu * self.scales[0]
        if r.memory > 0:
            v[1] = r.memory * self.scales[1]
        for name, quant in r.scalars.items():
            i = self.index.get(name)
            if i is not None:
                v[i] = quant * self.scales[i]
        return v


NODE_BUCKET = 256
TASK_BUCKET = 256
GROUP_BUCKET = 16


@dataclass
class NodeArrays:
    """Per-node resource state, padded to N_pad (valid mask marks real rows)."""

    rindex: ResourceIndex
    names: List[str]                 # real node names, index-aligned
    name_to_idx: Dict[str, int]
    n_pad: int
    valid: np.ndarray                # [N] bool
    idle: np.ndarray                 # [N, R] f32
    used: np.ndarray
    releasing: np.ndarray
    pipelined: np.ndarray
    allocatable: np.ndarray
    capability: np.ndarray
    max_tasks: np.ndarray            # [N] i32 (pods capacity; 0 => unlimited)
    n_tasks: np.ndarray              # [N] i32 current task count
    revocable: np.ndarray            # [N] bool
    oversubscription: np.ndarray     # [N] bool

    @classmethod
    def build(cls, nodes: Dict[str, NodeInfo], node_order: Sequence[str],
              rindex: Optional[ResourceIndex] = None,
              node_bucket: int = NODE_BUCKET) -> "NodeArrays":
        names = [n for n in node_order if n in nodes]
        if rindex is None:
            rindex = ResourceIndex.from_cluster(nodes, {})
        n_pad = bucket(len(names), node_bucket)
        r = rindex.r
        z = lambda: np.zeros((n_pad, r), np.float32)
        arr = cls(rindex=rindex, names=names,
                  name_to_idx={n: i for i, n in enumerate(names)},
                  n_pad=n_pad, valid=np.zeros(n_pad, bool),
                  idle=z(), used=z(), releasing=z(), pipelined=z(),
                  allocatable=z(), capability=z(),
                  max_tasks=np.zeros(n_pad, np.int32),
                  n_tasks=np.zeros(n_pad, np.int32),
                  revocable=np.zeros(n_pad, bool),
                  oversubscription=np.zeros(n_pad, bool))
        views = (arr.idle, arr.used, arr.releasing, arr.pipelined,
                 arr.allocatable, arr.capability)
        index = rindex.index
        n = len(names)
        infos = [nodes[name] for name in names]
        arr.valid[:n] = True
        if r == 2:
            # no scalar dimensions anywhere: column-wise fromiter fills
            # (the per-node row loop cost ~4 us x 10k nodes per build)
            for view, attr in zip(views, ("idle", "used", "releasing",
                                          "pipelined", "allocatable",
                                          "capability")):
                view[:n, 0] = np.fromiter(
                    (getattr(ni, attr).milli_cpu for ni in infos),
                    np.float32, n)
                view[:n, 1] = np.fromiter(
                    (getattr(ni, attr).memory for ni in infos),
                    np.float32, n)
        else:
            for i, ni in enumerate(infos):
                # direct field writes instead of rindex.vec() (6 temp-array
                # allocations per node dominated the encode at 10k nodes);
                # scaling applied once per block below
                for view, res in zip(views, (ni.idle, ni.used, ni.releasing,
                                             ni.pipelined, ni.allocatable,
                                             ni.capability)):
                    row = view[i]
                    row[0] = res.milli_cpu
                    row[1] = res.memory
                    if res.scalars:
                        for sname, quant in res.scalars.items():
                            si = index.get(sname)
                            if si is not None:
                                row[si] = quant
        arr.max_tasks[:n] = np.fromiter(
            (ni.allocatable.max_task_num for ni in infos), np.int32, n)
        arr.n_tasks[:n] = np.fromiter(
            (len(ni.tasks) for ni in infos), np.int32, n)
        arr.revocable[:n] = np.fromiter(
            (bool(ni.revocable_zone) for ni in infos), bool, n)
        arr.oversubscription[:n] = np.fromiter(
            (ni.oversubscription_node for ni in infos), bool, n)
        for view in views:
            view *= rindex.scales[None, :]
        return arr

    @property
    def future_idle(self) -> np.ndarray:
        return self.idle + self.releasing - self.pipelined

    def update_rows(self, nodes: Dict[str, NodeInfo], names) -> List[int]:
        """Re-encode the rows of ``names`` in place from the live
        NodeInfos — the incremental steady-state path (docs/design/
        incremental_cycle.md) keeps ONE NodeArrays alive across cycles
        and re-encodes only the dirty rows. Same field semantics as
        :meth:`build`; membership/order changes are the caller's problem
        (it must full-rebuild instead). Returns the updated row indices.
        """
        views = ("idle", "used", "releasing", "pipelined", "allocatable",
                 "capability")
        index = self.rindex.index
        scales = self.rindex.scales
        rows: List[int] = []
        for name in names:
            i = self.name_to_idx.get(name)
            ni = nodes.get(name)
            if i is None or ni is None:
                continue
            rows.append(i)
            for attr in views:
                res = getattr(ni, attr)
                row = getattr(self, attr)[i]
                row[:] = 0.0
                row[0] = res.milli_cpu
                row[1] = res.memory
                if res.scalars:
                    for sname, quant in res.scalars.items():
                        si = index.get(sname)
                        if si is not None:
                            row[si] = quant
                row *= scales
            self.max_tasks[i] = ni.allocatable.max_task_num
            self.n_tasks[i] = len(ni.tasks)
            self.revocable[i] = bool(ni.revocable_zone)
            self.oversubscription[i] = ni.oversubscription_node
        return rows


_SIG_INTERN: Dict[tuple, int] = {}
_SIG_LOCK = threading.Lock()
_SIG_NEXT = 0                      # monotone: ids are never reused
_SIG_INTERN_MAX = 1_000_000        # keys (incl. affinity reprs) are dropped
#                                    past this; a re-interned key gets a NEW
#                                    id, which can only split a group (safe),
#                                    never merge two distinct ones


def _group_sig(t: TaskInfo) -> int:
    """Small-int intern of (task template, request, constraints): the
    group identity of a task within its job, so the 50k-task encode loop
    hashes two ints per task instead of a nested tuple-of-tuples.

    Cached on the *Pod* object (not just the TaskInfo): session tasks are
    fresh clones every cycle, but they share the cache's pod until an
    update replaces it — exactly the lifetime over which all three key
    parts are immutable. The TaskInfo-level cache then short-circuits
    repeat encodes within one session (preempt/reclaim contexts)."""
    sig = t.group_sig_cache
    if sig is None:
        pod = t.pod
        sig = pod.__dict__.get("_sched_group_sig")
        if sig is None:
            global _SIG_NEXT
            key = (t.task_id, _req_key(t), _constraint_key(t))
            with _SIG_LOCK:
                sig = _SIG_INTERN.get(key)
                if sig is None:
                    if len(_SIG_INTERN) >= _SIG_INTERN_MAX:
                        _SIG_INTERN.clear()   # bound memory; ids stay unique
                    sig = _SIG_NEXT
                    _SIG_NEXT += 1
                    _SIG_INTERN[key] = sig
            pod._sched_group_sig = sig
        t.group_sig_cache = sig
    return sig


def _constraint_key(t: TaskInfo) -> tuple:
    """Scheduling-constraint fingerprint for grouping: tasks with identical
    constraints share predicate masks. Cached on the TaskInfo (constraints
    are immutable for a pod's lifetime; the repr() of affinity trees is the
    expensive part at 50k tasks)."""
    cached = t.constraint_key_cache
    if cached is not None:
        return cached
    spec = t.pod.spec
    if not spec.node_selector and not spec.tolerations \
            and spec.affinity is None and not spec.topology_spread:
        key = _TRIVIAL_CONSTRAINT          # the overwhelmingly common shape
    else:
        sel = tuple(sorted(spec.node_selector.items()))
        tol = tuple(sorted((x.key, x.operator, x.value, x.effect)
                           for x in spec.tolerations))
        aff = repr(spec.affinity) if spec.affinity is not None else ""
        spread = tuple((c.topology_key, c.max_skew, c.when_unsatisfiable,
                        repr(c.label_selector))
                       for c in spec.topology_spread)
        key = (sel, tol, aff, spread)
    t.constraint_key_cache = key
    return key


_TRIVIAL_CONSTRAINT = ((), (), "", ())


def derived_sig(base_sig: int, tag) -> int:
    """A stable intern id for a DERIVED group identity — the constraint
    compiler splits a spread-constrained task group into per-topology-slot
    subgroups (ops/constraints.py), and the subgroup sig must live in the
    same id space as :func:`_group_sig` without ever colliding with a
    pod-level sig. Same intern table, key namespaced by a marker."""
    global _SIG_NEXT
    key = ("__derived__", base_sig, tag)
    with _SIG_LOCK:
        sig = _SIG_INTERN.get(key)
        if sig is None:
            if len(_SIG_INTERN) >= _SIG_INTERN_MAX:
                _SIG_INTERN.clear()
            sig = _SIG_NEXT
            _SIG_NEXT += 1
            _SIG_INTERN[key] = sig
    return sig


def _req_key(t: TaskInfo) -> tuple:
    cached = t.req_key_cache
    if cached is not None:
        return cached
    r = t.resreq
    if r.scalars:
        key = (r.milli_cpu, r.memory, tuple(sorted(r.scalars.items())))
    else:
        key = (r.milli_cpu, r.memory)
    t.req_key_cache = key
    return key


@dataclass
class TaskBatch:
    """An ordered batch of pending tasks to place, with group compression.

    Jobs are regrouped so that each (namespace, queue) POOL's jobs form one
    contiguous span. Namespace indices follow first appearance (the caller
    feeds jobs namespace-sorted by the session's NamespaceOrderFn, so the
    static index order IS the session-open namespace order); queue indices
    follow first appearance across the batch. The kernel *dynamically*
    re-selects the namespace, then the queue, at every job boundary
    (allocate.go:120-162), so the encode order only decides ties.
    """

    rindex: ResourceIndex
    tasks: List[TaskInfo]            # real tasks, scan order
    t_pad: int
    g_pad: int
    j_pad: int
    q_pad: int
    task_valid: np.ndarray           # [T] bool
    task_group: np.ndarray           # [T] i32
    task_job: np.ndarray             # [T] i32
    group_req: np.ndarray            # [G, R] f32
    group_first: np.ndarray          # [G_real] i32 first task per group
    group_inverse: np.ndarray        # [T_real] group of each task
    job_uids: List[str]
    job_min_available: np.ndarray    # [J] i32 (padding rows incl. sentinel: 0)
    job_ready_base: np.ndarray       # [J] i32 already-occupied task count
    job_task_start: np.ndarray       # [J] i32 span starts in scan order
    job_task_end: np.ndarray         # [J] i32
    job_queue: np.ndarray            # [J] i32 queue index (padding: 0)
    queue_names: List[str]           # first-appearance queue order
    ns_names: List[str]              # first-appearance namespace order
    pool_queue: np.ndarray           # [P] i32 queue of each (ns, queue) pool
    pool_ns: np.ndarray              # [P] i32 namespace of each pool
    pool_job_start: np.ndarray       # [P] i32 jobs grouped by pool
    pool_njobs: np.ndarray           # [P] i32
    # per-task topology-domain restriction (ops/constraints.py
    # build_slot_tensors, set post-build by the solver's context build):
    # task_slot[t] indexes a slot_rows row; row S is all-true and
    # unconstrained tasks carry S. None = no batch task carries a slot.
    task_slot: Optional[np.ndarray] = None       # [T] i32
    slot_rows: Optional[np.ndarray] = None       # [S+1, n_pad] bool

    @property
    def job_n_tasks(self) -> np.ndarray:
        return self.job_task_end - self.job_task_start

    @classmethod
    def build(cls, ordered_jobs: Sequence[Tuple[JobInfo, Sequence[TaskInfo]]],
              rindex: ResourceIndex,
              task_bucket: int = TASK_BUCKET,
              group_bucket: int = GROUP_BUCKET,
              sig_override: Optional[Dict[str, int]] = None) -> "TaskBatch":
        # regroup jobs by (namespace, queue) pool, stable: namespace and
        # queue order = first appearance; zero-task jobs are excluded (each
        # job consumes scan steps equal to its task count, so empty jobs
        # would starve the T-step budget — the caller resolves their
        # readiness from existing occupancy instead)
        queue_names: List[str] = []
        queue_idx: Dict[str, int] = {}
        ns_names: List[str] = []
        ns_idx: Dict[str, int] = {}
        pool_order: List[Tuple[int, int]] = []     # (ns, queue) per pool
        by_pool: Dict[Tuple[int, int], list] = {}
        for job, jtasks in ordered_jobs:
            if not jtasks:
                continue
            qname = getattr(job, "queue", "") or ""
            if qname not in queue_idx:
                queue_idx[qname] = len(queue_names)
                queue_names.append(qname)
            nsname = getattr(job, "namespace", "") or ""
            if nsname not in ns_idx:
                ns_idx[nsname] = len(ns_names)
                ns_names.append(nsname)
            key = (ns_idx[nsname], queue_idx[qname])
            if key not in by_pool:
                by_pool[key] = []
                pool_order.append(key)
            by_pool[key].append((job, jtasks))

        tasks: List[TaskInfo] = []
        task_sig: List[int] = []
        task_job: List[int] = []
        job_uids: List[str] = []
        job_min: List[int] = []
        job_base: List[int] = []
        job_start: List[int] = []
        job_end: List[int] = []
        job_queue: List[int] = []
        pool_queue: List[int] = []
        pool_ns: List[int] = []
        pool_job_start: List[int] = []
        pool_njobs: List[int] = []

        for key in pool_order:
            ns_i, q_idx = key
            pool_ns.append(ns_i)
            pool_queue.append(q_idx)
            pool_job_start.append(len(job_uids))
            pool_njobs.append(len(by_pool[key]))
            for job, jtasks in by_pool[key]:
                j_idx = len(job_uids)
                job_uids.append(job.uid)
                job_min.append(job.min_available)
                job_base.append(job.ready_task_num())
                job_start.append(len(tasks))
                job_queue.append(q_idx)
                tasks.extend(jtasks)
                if sig_override:
                    # per-cycle derived sigs (spread slots) win over the
                    # pod-level identity; everything else keeps the
                    # cached/interned path
                    task_sig.extend(
                        ov if (ov := sig_override.get(t.uid)) is not None
                        else (t.group_sig_cache if t.group_sig_cache
                              is not None else _group_sig(t))
                        for t in jtasks)
                else:
                    task_sig.extend(t.group_sig_cache if t.group_sig_cache
                                    is not None else _group_sig(t)
                                    for t in jtasks)
                task_job.extend([j_idx] * len(jtasks))
                job_end.append(len(tasks))

        # group assignment, vectorized: pack (job, sig) into one int64 and
        # unique it. Group ids come out key-sorted (job-major) instead of
        # first-appearance — opaque to every consumer (they index rows).
        if tasks:
            sig_arr = np.asarray(task_sig, np.int64)
            if sig_arr.size and int(sig_arr.max()) >= (1 << 32):
                # the monotone intern ids passed 2^32 (years of churn):
                # densify this batch's sigs to 0..K-1 (K <= T) so the
                # 32-bit pack stays collision-free and exact
                _, sig_arr = np.unique(sig_arr, return_inverse=True)
                sig_arr = sig_arr.astype(np.int64)
            packed = (np.asarray(task_job, np.int64) << 32) | sig_arr
            uniq_keys, first_idx, inverse = np.unique(
                packed, return_index=True, return_inverse=True)
            task_group = inverse.astype(np.int32)
            reps = [tasks[i] for i in first_idx]
            if all(not r.resreq.scalars for r in reps):
                # no scalar dims: column-wise fill beats one rindex.vec
                # (6 temp arrays) per group — 6k groups per burst encode
                n_g = len(reps)
                group_reqs_arr = np.zeros((n_g, rindex.r), np.float32)
                group_reqs_arr[:, 0] = np.fromiter(
                    (r.resreq.milli_cpu for r in reps), np.float64, n_g)
                group_reqs_arr[:, 1] = np.fromiter(
                    (r.resreq.memory for r in reps), np.float64, n_g)
                group_reqs_arr *= rindex.scales[None, :]
                group_reqs = group_reqs_arr
            else:
                group_reqs = [rindex.vec(t.resreq) for t in reps]
            group_first = first_idx.astype(np.int32)
            group_inverse = inverse
        else:
            task_group = np.zeros(0, np.int32)
            group_reqs = []
            group_first = np.zeros(0, np.int32)
            group_inverse = np.zeros(0, np.int64)

        t_pad = bucket(len(tasks), task_bucket)
        g_pad = bucket(max(1, len(group_reqs)), group_bucket)
        # one spare sentinel job absorbs padding tasks: it is never selected
        # (it belongs to no pool span) and its ready/kept stay False
        sentinel = len(job_uids)
        j_pad = bucket(len(job_uids) + 1, group_bucket)
        q_pad = bucket(max(1, len(queue_names)), 8)
        p_pad = bucket(max(1, len(pool_queue)), 8)
        r = rindex.r

        def pad1(a, n, dtype, fill=0):
            out = np.full(n, fill, dtype)
            if len(a):
                out[:len(a)] = a
            return out

        greq = np.zeros((g_pad, r), np.float32)
        if len(group_reqs):
            if isinstance(group_reqs, np.ndarray):
                greq[:len(group_reqs)] = group_reqs
            else:
                greq[:len(group_reqs)] = np.stack(group_reqs)

        return cls(
            rindex=rindex, tasks=tasks, t_pad=t_pad, g_pad=g_pad, j_pad=j_pad,
            q_pad=q_pad,
            task_valid=pad1(np.ones(len(tasks), bool), t_pad, bool),
            task_group=pad1(task_group, t_pad, np.int32),
            task_job=pad1(task_job, t_pad, np.int32, fill=sentinel),
            group_req=greq,
            group_first=group_first,
            group_inverse=group_inverse,
            job_uids=job_uids,
            job_min_available=pad1(job_min, j_pad, np.int32),
            job_ready_base=pad1(job_base, j_pad, np.int32),
            job_task_start=pad1(job_start, j_pad, np.int32),
            job_task_end=pad1(job_end, j_pad, np.int32),
            job_queue=pad1(job_queue, j_pad, np.int32),
            queue_names=queue_names,
            ns_names=ns_names,
            pool_queue=pad1(pool_queue, p_pad, np.int32),
            pool_ns=pad1(pool_ns, p_pad, np.int32),
            pool_job_start=pad1(pool_job_start, p_pad, np.int32),
            pool_njobs=pad1(pool_njobs, p_pad, np.int32),
        )

    @property
    def n_tasks(self) -> int:
        return len(self.tasks)

    @property
    def n_groups(self) -> int:
        return len(self.group_first)

    @property
    def group_members(self) -> List[List[int]]:
        """group -> member task indices, materialized on first use (most
        cycles only ever need a group's REPRESENTATIVE, group_first; the
        6k-list materialization cost real encode time per burst)."""
        cached = self.__dict__.get("_group_members")
        if cached is None:
            if len(self.group_inverse):
                order = np.argsort(self.group_inverse, kind="stable")
                counts = np.bincount(self.group_inverse,
                                     minlength=len(self.group_first))
                bounds = np.cumsum(counts)[:-1]
                cached = [m.tolist() for m in np.split(order, bounds)]
            else:
                cached = []
            self.__dict__["_group_members"] = cached
        return cached


# ---------------------------------------------------------------------------
# Feature matrices: label/taint/affinity matching as integer matmuls
# ---------------------------------------------------------------------------

@dataclass
class PredicateFeatures:
    """Boolean feature matrices for the predicate kernels.

    * ``node_pairs`` [N, F]: node has label pair f (pair = referenced
      (key,value) from any group's selector / required node affinity)
    * ``group_requires`` [G, F]: group's conjunctive required pairs
    * ``group_require_counts`` [G]: number of required pairs per group
    * ``node_taints`` [N, K]: node carries (NoSchedule|NoExecute) taint k
    * ``group_tolerates`` [G, K]: group tolerates taint k
    * ``group_affinity_ok`` [G, N]: OR-of-terms node affinity evaluated for
      expression forms beyond In-pairs (Exists/Gt/Lt/NotIn), host-encoded;
      ``None`` when no group carries required node affinity — a [G, N]
      all-ones matrix is ~64MB at 50k x 10k and host->device shipping it
      every cycle would dominate the solver on a tunneled TPU
    """

    node_pairs: np.ndarray
    group_requires: np.ndarray
    group_require_counts: np.ndarray
    node_taints: np.ndarray
    group_tolerates: np.ndarray
    group_affinity_ok: Optional[np.ndarray]

    @classmethod
    def build(cls, nodes: Dict[str, NodeInfo], node_arrays: NodeArrays,
              batch: TaskBatch,
              slot_entries: Optional[Dict[str, tuple]] = None
              ) -> "PredicateFeatures":
        """``slot_entries`` ({task uid: ((key, values, hard), ...)}) are
        the constraint compiler's spread/anti-affinity domain
        assignments (ops/constraints.py): each lowers to a required
        (key, value) label pair — or, for an unsatisfiable empty
        assignment, a sentinel pair no node carries — so topology
        constraints ride the same compact selector matmul as node
        selectors instead of a dense [G, N] mask build + transfer."""
        n_pad = node_arrays.n_pad
        g_pad = batch.g_pad
        # one representative task per group (tasks group on identical
        # constraints, so the rep carries them for the whole group;
        # derived slot groups key on the entries, so the rep's slot
        # assignment is the whole group's)
        reps = [batch.tasks[i] for i in batch.group_first]

        # taints (NoSchedule/NoExecute block scheduling): node-side, needed
        # regardless of task constraints — an untolerated taint must mask
        # its node even for constraint-free pods
        taint_ids: Dict[tuple, int] = {}
        node_taint_list: List[List[int]] = [[] for _ in range(n_pad)]
        for name, i in node_arrays.name_to_idx.items():
            node = nodes[name].node
            for taint in (node.spec.taints if node else []):
                if taint.effect in ("NoSchedule", "NoExecute"):
                    tid = taint_ids.setdefault(
                        (taint.key, taint.value, taint.effect),
                        len(taint_ids))
                    node_taint_list[i].append(tid)
        k_pad = bucket(max(1, len(taint_ids)), 8)
        node_taints = np.zeros((n_pad, k_pad), np.float32)
        for i, tids in enumerate(node_taint_list):
            for tid in tids:
                node_taints[i, tid] = 1.0

        # fast path: no group carries any scheduling constraint — the
        # common burst shape; skip every per-group sweep (the group-side
        # matrices are all-zero / trivially empty)
        if not slot_entries and \
                all(t.constraint_key_cache is _TRIVIAL_CONSTRAINT or (
                    not t.pod.spec.node_selector
                    and not t.pod.spec.tolerations
                    and t.pod.spec.affinity is None
                    and not t.pod.spec.topology_spread) for t in reps):
            f_pad = bucket(1, 8)
            return cls(
                node_pairs=np.zeros((n_pad, f_pad), np.float32),
                group_requires=np.zeros((g_pad, f_pad), np.float32),
                group_require_counts=np.zeros(g_pad, np.float32),
                node_taints=node_taints,
                group_tolerates=np.zeros((g_pad, k_pad), np.float32),
                group_affinity_ok=None)

        # collect referenced selector pairs (+ the compiler's assigned
        # topology domains: required pairs with identical semantics)
        pair_ids: Dict[Tuple[str, str], int] = {}
        group_pairs: List[List[int]] = [[] for _ in range(g_pad)]
        _UNSAT = ("__constraint_unsat__", "__constraint_unsat__")
        for g, t in enumerate(reps):
            for k, v in sorted(t.pod.spec.node_selector.items()):
                pid = pair_ids.setdefault((k, v), len(pair_ids))
                group_pairs[g].append(pid)
            entries = slot_entries.get(t.uid) if slot_entries else None
            for key, values, _hard in entries or ():
                pair = (key, values[0]) if values else _UNSAT
                pid = pair_ids.setdefault(pair, len(pair_ids))
                group_pairs[g].append(pid)

        f_pad = bucket(max(1, len(pair_ids)), 8)
        node_pairs = np.zeros((n_pad, f_pad), np.float32)
        if pair_ids:   # no referenced pairs -> skip the 10k-node label sweep
            for name, i in node_arrays.name_to_idx.items():
                labels = nodes[name].node.metadata.labels \
                    if nodes[name].node else {}
                for (k, v), pid in pair_ids.items():
                    if labels.get(k) == v:
                        node_pairs[i, pid] = 1.0

        group_requires = np.zeros((g_pad, f_pad), np.float32)
        for g, pids in enumerate(group_pairs):
            for pid in pids:
                group_requires[g, pid] = 1.0
        group_require_counts = group_requires.sum(axis=1).astype(np.float32)

        group_tolerates = np.zeros((g_pad, k_pad), np.float32)
        from .objects import Taint
        for g, t in enumerate(reps):
            for (key, value, effect), tid in taint_ids.items():
                taint = Taint(key=key, value=value, effect=effect)
                if any(tol.tolerates(taint) for tol in t.pod.spec.tolerations):
                    group_tolerates[g, tid] = 1.0

        # full node-affinity evaluation (any expression form), host-encoded
        # per group x node; built only when some group actually carries
        # required affinity (None otherwise — see class docstring)
        group_affinity_ok = None
        for g, t in enumerate(reps):
            aff = t.pod.spec.affinity
            if aff is None or aff.node_affinity is None or not aff.node_affinity.required:
                continue
            if group_affinity_ok is None:
                group_affinity_ok = np.ones((g_pad, n_pad), bool)
            terms = aff.node_affinity.required
            for name, i in node_arrays.name_to_idx.items():
                labels = nodes[name].node.metadata.labels if nodes[name].node else {}
                group_affinity_ok[g, i] = any(term.matches(labels) for term in terms)

        return cls(node_pairs=node_pairs, group_requires=group_requires,
                   group_require_counts=group_require_counts,
                   node_taints=node_taints, group_tolerates=group_tolerates,
                   group_affinity_ok=group_affinity_ok)
