"""Resource vectors: the arithmetic every fit/fairness decision rests on.

Behavioral contract mirrors the reference's Resource type
(reference: pkg/scheduler/api/resource_info.go:50-533):

* dimensions: cpu (millicores), memory (bytes), plus named scalar resources
  (accounted in milli-units), and a ``pods`` capacity that is only consulted
  by predicates (``max_task_num``), never by arithmetic.
* an epsilon of 0.1 (``EPS``) on all tolerant comparisons.
* comparisons take a *dimension default* for scalar resources absent from one
  side: ``Zero`` (treat missing as 0) or ``Infinity`` (treat missing as
  unbounded).  Internally missing-with-Infinity becomes ``math.inf`` which
  reproduces the reference's ``-1`` sentinel logic exactly (an infinite left
  side is never "less", an infinite right side always admits).

The class is the host-side object model; the dense array view used by the
TPU kernels is built by :mod:`volcano_tpu.models.arrays` over a
:class:`ResourceNameRegistry`.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional

from .quantity import milli_value, parse_quantity

# Epsilon for tolerant comparisons (reference: resource_info.go:36 minResource).
EPS: float = 0.1

# Dimension defaults (reference: resource_info.go:42-47).
ZERO = "Zero"
INFINITY = "Infinity"

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
GPU_RESOURCE_NAME = "nvidia.com/gpu"
# GPU-share scalar used by the gpu-share predicate (reference: plugins/predicates/gpu.go).
GPU_MEMORY_RESOURCE = "volcano.sh/gpu-memory"
GPU_NUMBER_RESOURCE = "volcano.sh/gpu-number"


def _is_scalar_name(name: str) -> bool:
    """Names other than cpu/memory/pods are scalar (extended) resources."""
    return name not in (CPU, MEMORY, PODS)


class Resource:
    """A mutable resource vector (cpu millicores, memory bytes, scalars)."""

    __slots__ = ("milli_cpu", "memory", "scalars", "max_task_num")

    def __init__(self, milli_cpu: float = 0.0, memory: float = 0.0,
                 scalars: Optional[Dict[str, float]] = None, max_task_num: int = 0):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalars: Dict[str, float] = dict(scalars) if scalars else {}
        self.max_task_num = int(max_task_num)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_resource_list(cls, rl: Optional[Dict[str, object]]) -> "Resource":
        """Build from a {"cpu": "2", "memory": "4Gi", ...} mapping.

        cpu -> millicores, memory -> bytes, pods -> max_task_num, any other
        name -> scalar milli-units (reference: resource_info.go:69-88).
        """
        r = cls()
        if not rl:
            return r
        for name, quant in rl.items():
            if name == CPU:
                r.milli_cpu += milli_value(quant)
            elif name == MEMORY:
                r.memory += parse_quantity(quant)
            elif name == PODS:
                r.max_task_num += int(parse_quantity(quant))
            else:
                r.add_scalar(name, milli_value(quant))
        return r

    def clone(self) -> "Resource":
        # __new__ + direct assigns: the constructor's float()/int() casts
        # cost real time at ~60k clones per 50k-task snapshot
        c = Resource.__new__(Resource)
        c.milli_cpu = self.milli_cpu
        c.memory = self.memory
        c.scalars = dict(self.scalars)
        c.max_task_num = self.max_task_num
        return c

    def to_resource_list(self) -> Dict[str, object]:
        """Inverse of from_resource_list (cpu/scalars as "<milli>m" strings,
        memory as bytes). Used when writing PodGroup.spec.min_resources."""
        rl: Dict[str, object] = {}
        if self.milli_cpu:
            rl[CPU] = f"{self.milli_cpu:g}m"
        if self.memory:
            rl[MEMORY] = self.memory
        if self.max_task_num:
            rl[PODS] = self.max_task_num
        for name, value in self.scalars.items():
            rl[name] = f"{value:g}m"
        return rl

    # -- access ------------------------------------------------------------

    def get(self, name: str) -> float:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        return self.scalars.get(name, 0.0)

    def set(self, name: str, value: float) -> None:
        if name == CPU:
            self.milli_cpu = value
        elif name == MEMORY:
            self.memory = value
        else:
            self.scalars[name] = value

    def resource_names(self) -> Iterable[str]:
        return [CPU, MEMORY, *self.scalars.keys()]

    def is_empty(self) -> bool:
        """True iff every dimension is below EPS (resource_info.go:144-156)."""
        if self.milli_cpu >= EPS or self.memory >= EPS:
            return False
        return all(q < EPS for q in self.scalars.values())

    def is_zero(self, name: str) -> bool:
        """Whether one dimension is below EPS; unknown scalar names are zero."""
        if name == CPU:
            return self.milli_cpu < EPS
        if name == MEMORY:
            return self.memory < EPS
        return self.scalars.get(name, 0.0) < EPS

    # -- arithmetic (mutating, returning self, like the reference) ---------

    def add(self, rr: "Resource") -> "Resource":
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        for name, quant in rr.scalars.items():
            self.scalars[name] = self.scalars.get(name, 0.0) + quant
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; requires rr <= self under Zero defaults (resource_info.go:195)."""
        assert rr.less_equal(self, ZERO), \
            f"resource is not sufficient to do operation: <{self}> sub <{rr}>"
        return self.sub_unchecked(rr)

    def sub_unchecked(self, rr: "Resource") -> "Resource":
        """sub() without the sufficiency assertion — for hot paths whose
        caller has just performed the same less_equal check (e.g.
        NodeInfo._allocate_idle); the assertion would re-run it per call."""
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        if not self.scalars:
            return self
        for name, quant in rr.scalars.items():
            self.scalars[name] = self.scalars.get(name, 0.0) - quant
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        for name in self.scalars:
            self.scalars[name] *= ratio
        return self

    def set_max_resource(self, rr: "Resource") -> None:
        """Per-dimension max, in place (resource_info.go:218-243)."""
        self.milli_cpu = max(self.milli_cpu, rr.milli_cpu)
        self.memory = max(self.memory, rr.memory)
        for name, quant in rr.scalars.items():
            if name not in self.scalars or quant > self.scalars[name]:
                self.scalars[name] = quant

    def fit_delta(self, rr: "Resource") -> "Resource":
        """available - (requested + EPS) per requested dimension; negative
        entries mean insufficiency (resource_info.go:246-274)."""
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + EPS
        if rr.memory > 0:
            self.memory -= rr.memory + EPS
        for name, quant in rr.scalars.items():
            if quant > 0:
                self.scalars[name] = self.scalars.get(name, 0.0) - (quant + EPS)
        return self

    def min_dimension_resource(self, rr: "Resource") -> "Resource":
        """Clamp self per-dimension to rr.  When rr carries no scalar map at
        all, self's scalars are zeroed; otherwise only names present in rr
        are clamped (resource_info.go:477-504)."""
        self.milli_cpu = min(self.milli_cpu, rr.milli_cpu)
        self.memory = min(self.memory, rr.memory)
        if not rr.scalars:
            for name in self.scalars:
                self.scalars[name] = 0.0
        else:
            for name, quant in rr.scalars.items():
                if name in self.scalars and quant < self.scalars[name]:
                    self.scalars[name] = quant
        return self

    def diff(self, rr: "Resource"):
        """Return (increased, decreased) per-dimension differences; scalar
        names are drawn from self's side only (resource_info.go:426-460)."""
        inc, dec = Resource(), Resource()
        for name in (CPU, MEMORY, *self.scalars.keys()):
            l, r = self.get(name), rr.get(name)
            if l > r:
                inc.set(name, l - r)
            else:
                dec.set(name, r - l)
        return inc, dec

    def add_scalar(self, name: str, quantity: float) -> None:
        self.scalars[name] = self.scalars.get(name, 0.0) + quantity

    def set_scalar(self, name: str, quantity: float) -> None:
        self.scalars[name] = quantity

    # -- comparisons -------------------------------------------------------

    def _scalar_pairs(self, rr: "Resource", default: str):
        """Union of scalar names with missing entries defaulted; Infinity
        becomes math.inf, reproducing the -1 sentinel branches
        (resource_info.go:506-533 setDefaultValue)."""
        fill = 0.0 if default == ZERO else math.inf
        names = set(self.scalars) | set(rr.scalars)
        for name in names:
            yield self.scalars.get(name, fill), rr.scalars.get(name, fill)

    def less(self, rr: "Resource", default: str = ZERO) -> bool:
        """Strictly less in *every* dimension (resource_info.go:276-308)."""
        if not (self.milli_cpu < rr.milli_cpu and self.memory < rr.memory):
            return False
        for l, r in self._scalar_pairs(rr, default):
            if r == math.inf:
                continue
            if l == math.inf or not l < r:
                return False
        return True

    def less_equal(self, rr: "Resource", default: str = ZERO) -> bool:
        """<= within EPS in every dimension (resource_info.go:310-341)."""
        def le(l, r):
            return l < r or abs(l - r) < EPS
        if not (le(self.milli_cpu, rr.milli_cpu) and le(self.memory, rr.memory)):
            return False
        if not self.scalars and not rr.scalars:
            return True   # fast path: the dominant case on the bind hot loop
        for l, r in self._scalar_pairs(rr, default):
            if r == math.inf:
                continue
            if l == math.inf or not le(l, r):
                return False
        return True

    def less_partly(self, rr: "Resource", default: str = ZERO) -> bool:
        """Strictly less in *some* dimension (resource_info.go:343-368)."""
        if self.milli_cpu < rr.milli_cpu or self.memory < rr.memory:
            return True
        for l, r in self._scalar_pairs(rr, default):
            if l == math.inf:
                continue
            if r == math.inf or l < r:
                return True
        return False

    def less_equal_partly(self, rr: "Resource", default: str = ZERO) -> bool:
        """<= within EPS in some dimension (resource_info.go:370-396)."""
        def le(l, r):
            return l < r or abs(l - r) < EPS
        if le(self.milli_cpu, rr.milli_cpu) or le(self.memory, rr.memory):
            return True
        for l, r in self._scalar_pairs(rr, default):
            if l == math.inf:
                continue
            if r == math.inf or le(l, r):
                return True
        return False

    def equal(self, rr: "Resource", default: str = ZERO) -> bool:
        """Equal within EPS in every dimension (resource_info.go:398-424)."""
        if not ((self.milli_cpu == rr.milli_cpu
                 or abs(self.milli_cpu - rr.milli_cpu) < EPS)
                and (self.memory == rr.memory
                     or abs(self.memory - rr.memory) < EPS)):
            return False
        if not self.scalars and not rr.scalars:
            return True   # fast path: the dominant case on the echo hot loop
        return all(l == r or abs(l - r) < EPS
                   for l, r in self._scalar_pairs(rr, default))

    # -- dunder sugar ------------------------------------------------------

    def __repr__(self) -> str:
        s = f"cpu {self.milli_cpu:.2f}, memory {self.memory:.2f}"
        for name, quant in sorted(self.scalars.items()):
            s += f", {name} {quant:.2f}"
        return s

    def __eq__(self, other) -> bool:
        return isinstance(other, Resource) and self.equal(other, ZERO)

    def __hash__(self):  # mutable; identity hash like Go pointers
        return id(self)

    def __add__(self, other: "Resource") -> "Resource":
        return self.clone().add(other)

    def __sub__(self, other: "Resource") -> "Resource":
        return self.clone().sub(other)


def empty_resource() -> Resource:
    return Resource()


def min_resource(a: Resource, b: Resource) -> Resource:
    return a.clone().min_dimension_resource(b)
