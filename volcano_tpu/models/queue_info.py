"""QueueInfo / NamespaceInfo (reference: pkg/scheduler/api/queue_info.go,
namespace_info.go)."""

from __future__ import annotations

from typing import Dict

from . import objects
from .objects import Queue, ResourceQuota


class QueueInfo:
    """Scheduler view of one Queue (queue_info.go:29-88)."""

    def __init__(self, queue: Queue):
        self.uid: str = queue.metadata.name
        self.name: str = queue.metadata.name
        self.weight: int = max(1, queue.spec.weight)
        self.queue: Queue = queue
        # hierarchical fair-share path: "root/sci/dev" with per-level weights
        self.hierarchy: str = queue.metadata.annotations.get(
            objects.QUEUE_HIERARCHY_ANNOTATION, "")
        self.hierarchical_weights: str = queue.metadata.annotations.get(
            objects.QUEUE_HIERARCHY_WEIGHT_ANNOTATION, "")

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    def reclaimable(self) -> bool:
        return self.queue.spec.reclaimable


DEFAULT_NAMESPACE_WEIGHT = 1
NAMESPACE_WEIGHT_KEY = "namespace.weight"


class NamespaceInfo:
    """Per-namespace weight from ResourceQuota objects
    (namespace_info.go:26-145)."""

    def __init__(self, name: str, weight: int = DEFAULT_NAMESPACE_WEIGHT):
        self.name = name
        self.weight = weight

    def get_weight(self) -> int:
        return self.weight if self.weight > 0 else DEFAULT_NAMESPACE_WEIGHT


class NamespaceCollection:
    """Tracks quota objects per namespace; weight = max over quotas of the
    namespace.weight hard field (namespace_info.go:55-145)."""

    def __init__(self, name: str):
        self.name = name
        self.quota_weight: Dict[str, int] = {}

    def update(self, quota: ResourceQuota) -> None:
        w = quota.hard.get(NAMESPACE_WEIGHT_KEY)
        if w is not None:
            self.quota_weight[quota.metadata.name] = int(float(w))
        else:
            self.quota_weight.pop(quota.metadata.name, None)

    def delete(self, quota: ResourceQuota) -> None:
        self.quota_weight.pop(quota.metadata.name, None)

    def snapshot(self) -> NamespaceInfo:
        if not self.quota_weight:
            return NamespaceInfo(self.name)
        return NamespaceInfo(self.name, max(self.quota_weight.values()))
