"""NodeInfo: per-node resource state machine.

Behavioral contract mirrors the reference (pkg/scheduler/api/node_info.go):
Idle/Used/Releasing/Pipelined accounting by task status (AddTask:341,
RemoveTask:388), FutureIdle = Idle + Releasing - Pipelined (:71-73),
oversubscription ingestion (:187-226), ready/phase state (:227-263), and
GPU-share device accounting (:264-289, 463-509 + device_info.go).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import objects
from .objects import Node
from .job_info import TaskInfo, TaskStatus
from .resource import EPS, GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE, Resource, ZERO


class GPUDevice:
    """One shareable GPU card (reference: pkg/scheduler/api/device_info.go:24-72)."""

    def __init__(self, gpu_id: int, memory: float):
        self.id = gpu_id
        self.memory = memory
        self.pod_map: Dict[str, float] = {}  # pod uid -> gpu memory used

    def get_pods_used_gpu_memory(self) -> float:
        return sum(self.pod_map.values())


def get_gpu_memory_of_pod(pod) -> float:
    """Requested volcano.sh/gpu-memory across containers (device_info.go)."""
    mem = 0.0
    for c in pod.spec.containers:
        req = Resource.from_resource_list(c.requests)
        mem += req.get(GPU_MEMORY_RESOURCE) / 1000.0  # stored in milli-units
    return mem


class NodeState:
    def __init__(self, phase: str = "Ready", reason: str = ""):
        self.phase = phase
        self.reason = reason


class NodeInfo:
    """Aggregated per-node scheduling state."""

    def __init__(self, node: Optional[Node] = None):
        self.name: str = ""
        self.node: Optional[Node] = node
        self.state = NodeState()
        self.releasing = Resource()
        self.pipelined = Resource()
        self.idle = Resource()
        self.used = Resource()
        self.allocatable = Resource()
        self.capability = Resource()
        self.tasks: Dict[str, TaskInfo] = {}
        self.numa_info = None            # NumatopoInfo, set by cache
        self.numa_scheduler_info = None
        self.numa_chg_flag: str = ""     # ""|"more"|"less" (NumaChgFlag)
        self.revocable_zone: str = ""
        self.others: Dict[str, object] = {}
        # topology labels the placement constraints read (zone/rack/...):
        # captured once per NodeInfo build — node labels are effectively
        # immutable for a Node object's lifetime (a relabel arrives as a
        # new Node through the watch, rebuilding the NodeInfo)
        self.topology: Dict[str, str] = {}
        self.gpu_devices: Dict[int, GPUDevice] = {}
        self.oversubscription_node: bool = False
        self.offline_job_evicting: bool = False
        self.oversubscription_resource = Resource()

        self._set_oversubscription(node)
        if node is not None:
            self.name = node.metadata.name
            alloc = Resource.from_resource_list(node.status.allocatable)
            self.idle = alloc.clone().add(self.oversubscription_resource)
            self.allocatable = alloc.clone().add(self.oversubscription_resource)
            self.capability = Resource.from_resource_list(node.status.capacity) \
                .add(self.oversubscription_resource)
        self._set_gpu_info(node)
        self._set_node_state(node)
        self._set_revocable_zone(node)

    # -- node-level state --------------------------------------------------

    def _set_oversubscription(self, node: Optional[Node]) -> None:
        """Oversubscription annotations (node_info.go:187-226)."""
        if node is None:
            return
        a = node.metadata.annotations
        self.oversubscription_node = a.get(objects.OVERSUBSCRIPTION_NODE_KEY, "").lower() == "true"
        self.offline_job_evicting = a.get(objects.OFFLINE_JOB_EVICTING_KEY, "").lower() == "true"
        res = a.get(objects.OVERSUBSCRIPTION_RESOURCE_KEY, "")
        if self.oversubscription_node and res:
            # "cpu:1000,memory:10Gi" style annotation
            rl = {}
            for part in res.split(","):
                if ":" in part:
                    k, v = part.split(":", 1)
                    rl[k.strip()] = v.strip()
            self.oversubscription_resource = Resource.from_resource_list(rl)

    def _set_node_state(self, node: Optional[Node]) -> None:
        """Ready iff node exists, schedulable and Ready (node_info.go:227-263)."""
        if node is None:
            self.state = NodeState("NotReady", "UnknownNode")
            return
        if node.spec.unschedulable:
            self.state = NodeState("NotReady", "Unschedulable")
            return
        if not node.status.ready:
            self.state = NodeState("NotReady", "NotReady")
            return
        self.state = NodeState("Ready")

    def _set_revocable_zone(self, node: Optional[Node]) -> None:
        if node is None:
            return
        self.revocable_zone = node.metadata.labels.get(objects.REVOCABLE_ZONE_LABEL, "")
        # topology label capture for the constraint compiler
        # (ops/constraints.py): the conventional topology.* namespace plus
        # the hostname identity key — arbitrary keys fall back to
        # :meth:`topology_value`'s label lookup
        labels = node.metadata.labels
        self.topology = {k: v for k, v in labels.items()
                         if k.startswith("topology.")
                         or k == "kubernetes.io/hostname"}

    def topology_value(self, key: str) -> Optional[str]:
        """The node's value for a topology key (zone/rack/hostname/...),
        None when the label is absent — absent-label nodes never satisfy a
        constraint over that key (upstream PodTopologySpread semantics)."""
        v = self.topology.get(key)
        if v is None and self.node is not None:
            v = self.node.metadata.labels.get(key)
        return v

    def _set_gpu_info(self, node: Optional[Node]) -> None:
        """Populate shareable GPU devices from capacity (node_info.go:264-289)."""
        if node is None:
            return
        cap = Resource.from_resource_list(node.status.capacity)
        mem_total = cap.get(GPU_MEMORY_RESOURCE) / 1000.0
        num = int(cap.get(GPU_NUMBER_RESOURCE) / 1000.0)
        if num > 0 and mem_total > 0:
            per_card = mem_total / num
            for i in range(num):
                self.gpu_devices[i] = GPUDevice(i, per_card)

    def ready(self) -> bool:
        return self.state.phase == "Ready"

    def refresh_numa_scheduler_info(self) -> None:
        """Sync scheduler-side NUMA view from the CRD-fed one, only widening
        (or narrowing when the kubelet shrank allocatable)
        (node_info.go:120-143 RefreshNumaSchedulerInfoByCrd)."""
        if self.numa_info is None:
            self.numa_scheduler_info = None
            return
        if self.numa_scheduler_info is None or self.numa_chg_flag == "more":
            self.numa_scheduler_info = self.numa_info.clone()
        elif self.numa_chg_flag == "less":
            tmp = self.numa_info.clone()
            for res, resinfo in tmp.numa_res_map.items():
                cur = self.numa_scheduler_info.numa_res_map.get(res)
                if cur is not None and len(cur.allocatable) >= len(resinfo.allocatable):
                    cur.allocatable = set(resinfo.allocatable)
                    cur.capacity = resinfo.capacity
        self.numa_chg_flag = ""

    def future_idle(self) -> Resource:
        """Idle + Releasing - Pipelined (node_info.go:71-73)."""
        return self.idle.clone().add(self.releasing).sub(self.pipelined)

    # -- task accounting ---------------------------------------------------

    def _allocate_idle(self, ti: TaskInfo) -> None:
        if not ti.resreq.less_equal(self.idle, ZERO):
            raise RuntimeError("selected node NotReady")
        self.idle.sub_unchecked(ti.resreq)   # checked on the line above

    def add_task(self, task: TaskInfo) -> None:
        """Add a task; accounting depends on its status (node_info.go:341-384).
        On error, both task and node are left unchanged."""
        if task.node_name and self.name and task.node_name != self.name:
            raise RuntimeError(
                f"task <{task.namespace}/{task.name}> already on different "
                f"node <{task.node_name}>")
        key = task.key()
        if key in self.tasks:
            raise RuntimeError(
                f"task <{task.namespace}/{task.name}> already on node <{self.name}>")
        ti = task.clone()
        if self.node is not None:
            if ti.status == TaskStatus.Releasing:
                self._allocate_idle(ti)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
                self.add_gpu_resource(ti.pod)
            elif ti.status == TaskStatus.Pipelined:
                self.pipelined.add(ti.resreq)
            else:
                self._allocate_idle(ti)
                self.used.add(ti.resreq)
                self.add_gpu_resource(ti.pod)
        task.node_name = self.name
        ti.node_name = self.name
        self.tasks[key] = ti

    def add_tasks_bulk(self, tasks: List[TaskInfo], pipelined: bool,
                       total: Optional[Resource] = None,
                       share_objects: bool = False) -> None:
        """Add many same-status tasks with one resource-accounting pass
        (the per-node form of :meth:`add_task` — the allocate hot path
        lands ~5 tasks per node per cycle, and per-task idle checks plus
        used/idle updates dominated staging cost).

        All-or-nothing: validates everything (node identity, duplicates,
        combined fit against idle) before mutating, so no mid-way rollback
        can be needed. The combined-sum fit check is equivalent to the
        per-task declining-idle sequence. Callers needing prefix
        (keep-partial) semantics use the per-task path."""
        keys = []
        seen = set()
        summing = total is None
        if summing:
            total = Resource()
        for task in tasks:
            if task.node_name and self.name and task.node_name != self.name:
                raise RuntimeError(
                    f"task <{task.namespace}/{task.name}> already on "
                    f"different node <{task.node_name}>")
            key = task.key()
            if key in self.tasks or key in seen:
                raise RuntimeError(f"task <{task.namespace}/{task.name}> "
                                   f"already on node <{self.name}>")
            keys.append(key)
            seen.add(key)
            if summing:
                total.add(task.resreq)
        if self.node is not None and not pipelined \
                and not total.less_equal(self.idle, ZERO):
            raise RuntimeError("selected node NotReady")
        if self.node is not None:
            if pipelined:
                self.pipelined.add(total)
            else:
                self.idle.sub_unchecked(total)
                self.used.add(total)
        # share_objects: store the caller's TaskInfo instead of a clone.
        # Safe ONLY when no status-class-crossing transition can hit the
        # stored view while it is on the node — the session staging path
        # qualifies (victim selection is Running-only, staged tasks are
        # Allocated/Pipelined/Binding, and discard removes before the
        # status moves back). The cache keeps clones: its evict path
        # relies on the stored view holding the pre-transition status.
        for key, task in zip(keys, tasks):
            ti = task if share_objects else task.clone()
            if self.node is not None and not pipelined:
                self.add_gpu_resource(ti.pod)
            task.node_name = self.name
            ti.node_name = self.name
            self.tasks[key] = ti

    def remove_task(self, ti: TaskInfo) -> None:
        """Remove a task, reversing its accounting (node_info.go:388-420)."""
        key = ti.key()
        task = self.tasks.get(key)
        if task is None:
            return
        if self.node is not None:
            if task.status == TaskStatus.Releasing:
                self.releasing.sub(task.resreq)
                self.idle.add(task.resreq)
                self.used.sub(task.resreq)
                self.sub_gpu_resource(ti.pod)
            elif task.status == TaskStatus.Pipelined:
                self.pipelined.sub(task.resreq)
            else:
                self.idle.add(task.resreq)
                self.used.sub(task.resreq)
                self.sub_gpu_resource(ti.pod)
        ti.node_name = ""
        del self.tasks[key]

    def update_task(self, ti: TaskInfo) -> None:
        self.remove_task(ti)
        self.add_task(ti)

    def transition_task(self, ti: TaskInfo) -> None:
        """Status-only transition for a task already on this node.

        Equivalent to :meth:`update_task` but applies the accounting
        *delta* for the Running<->Releasing flip (the preempt/reclaim
        eviction pair) instead of fully reversing and replaying six
        Resource ops plus a task clone — idle/used cancel out, only
        ``releasing`` moves (node_info.go:388-420 replayed pairwise)."""
        stored = self.tasks.get(ti.key())
        if stored is None or self.node is None:
            self.update_task(ti)
            return
        old, new = stored.status, ti.status
        if old == TaskStatus.Running and new == TaskStatus.Releasing:
            self.releasing.add(stored.resreq)
        elif old == TaskStatus.Releasing and new == TaskStatus.Running:
            self.releasing.sub(stored.resreq)
        elif old != new:
            self.update_task(ti)
            return
        stored.status = new

    def set_node(self, node: Node) -> None:
        """Re-ingest node object, rebasing Idle on allocatable minus current
        usage (node_info.go:291-327)."""
        self.name = node.metadata.name
        self.node = node
        self._set_oversubscription(node)
        self._set_node_state(node)
        self._set_revocable_zone(node)
        self._set_gpu_info(node)
        if not self.ready():
            return
        alloc = Resource.from_resource_list(node.status.allocatable) \
            .add(self.oversubscription_resource)
        self.allocatable = alloc.clone()
        self.capability = Resource.from_resource_list(node.status.capacity) \
            .add(self.oversubscription_resource)
        self.idle = alloc.clone()
        self.used = Resource()
        self.releasing = Resource()
        self.pipelined = Resource()
        tasks = list(self.tasks.values())
        self.tasks = {}
        for t in tasks:
            t2 = t.clone()
            t2.node_name = ""
            self.add_task(t2)

    def clone(self) -> "NodeInfo":
        """Direct field copy (node_info.go Clone's deepcopy semantics).

        The accounting state (idle/used/releasing/pipelined) is copied as-is
        rather than re-derived by replaying add_task — the snapshot must
        mirror the cache's state, and replaying costs O(tasks) resource
        arithmetic plus a quantity re-parse per node, which dominated the
        per-cycle snapshot at 10k nodes."""
        from .job_info import _fastmodel
        fm = _fastmodel()
        if fm is not None:
            try:
                tasks = fm.clone_task_dict(self.tasks)
            except TypeError:
                tasks = None
            if tasks is not None:
                # C shell copy + the fields needing fresh values — the
                # same set the Python path below rebuilds
                c = fm.shell_clone(self)
                c.releasing = fm.clone_resource(self.releasing)
                c.pipelined = fm.clone_resource(self.pipelined)
                c.idle = fm.clone_resource(self.idle)
                c.used = fm.clone_resource(self.used)
                c.tasks = tasks
                if self.numa_scheduler_info is not None:
                    c.numa_scheduler_info = self.numa_scheduler_info.clone()
                c.others = dict(self.others)
                if self.gpu_devices:
                    devices = {}
                    for i, d in self.gpu_devices.items():
                        nd = GPUDevice(d.id, d.memory)
                        nd.pod_map = dict(d.pod_map)
                        devices[i] = nd
                    c.gpu_devices = devices
                else:
                    c.gpu_devices = {}
                return c
        c = NodeInfo.__new__(NodeInfo)
        c.name = self.name
        c.node = self.node
        c.state = self.state
        c.releasing = self.releasing.clone()
        c.pipelined = self.pipelined.clone()
        c.idle = self.idle.clone()
        c.used = self.used.clone()
        # capacity vectors are only ever replaced wholesale (set_node),
        # never mutated in place — share them across clones
        c.allocatable = self.allocatable
        c.capability = self.capability
        c.tasks = {k: t.clone() for k, t in self.tasks.items()}
        c.numa_info = self.numa_info
        c.numa_scheduler_info = (self.numa_scheduler_info.clone()
                                 if self.numa_scheduler_info is not None else None)
        c.numa_chg_flag = self.numa_chg_flag
        c.revocable_zone = self.revocable_zone
        c.topology = self.topology   # immutable after build: share
        c.others = dict(self.others)
        devices = {}
        for i, d in self.gpu_devices.items():
            nd = GPUDevice(d.id, d.memory)
            nd.pod_map = dict(d.pod_map)
            devices[i] = nd
        c.gpu_devices = devices
        c.oversubscription_node = self.oversubscription_node
        c.offline_job_evicting = self.offline_job_evicting
        c.oversubscription_resource = self.oversubscription_resource
        return c

    def pods(self):
        return [t.pod for t in self.tasks.values()]

    # -- GPU share accounting (device_info.go) -----------------------------

    def get_devices_idle_gpu_memory(self) -> Dict[int, float]:
        return {i: d.memory - d.get_pods_used_gpu_memory()
                for i, d in self.gpu_devices.items()}

    def add_gpu_resource(self, pod) -> None:
        if not self.gpu_devices:
            return   # no shareable GPUs: skip the per-container req rebuild
        mem = get_gpu_memory_of_pod(pod)
        if mem <= EPS:
            return
        gpu_id = pod.metadata.annotations.get("volcano.sh/gpu-index")
        if gpu_id is None:
            return
        dev = self.gpu_devices.get(int(gpu_id))
        if dev is not None:
            dev.pod_map[pod.metadata.uid] = mem

    def sub_gpu_resource(self, pod) -> None:
        gpu_id = pod.metadata.annotations.get("volcano.sh/gpu-index")
        if gpu_id is None:
            return
        dev = self.gpu_devices.get(int(gpu_id))
        if dev is not None:
            dev.pod_map.pop(pod.metadata.uid, None)

    def __repr__(self):
        return (f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>, "
                f"releasing <{self.releasing}>, state <{self.state.phase}>")
