"""ClusterInfo: the per-cycle snapshot bundle
(reference: pkg/scheduler/api/cluster_info.go)."""

from __future__ import annotations

from typing import Dict, List

from .job_info import JobInfo
from .node_info import NodeInfo
from .queue_info import NamespaceInfo, QueueInfo


class ClusterInfo:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespaces: Dict[str, NamespaceInfo] = {}
        self.revocable_nodes: Dict[str, NodeInfo] = {}
        self.node_list: List[str] = []

    def __repr__(self):
        return (f"ClusterInfo(jobs={len(self.jobs)}, nodes={len(self.nodes)}, "
                f"queues={len(self.queues)})")
