"""ClusterInfo: the per-cycle snapshot bundle
(reference: pkg/scheduler/api/cluster_info.go)."""

from __future__ import annotations

from typing import Dict, List

from .job_info import JobInfo
from .node_info import NodeInfo
from .queue_info import NamespaceInfo, QueueInfo


class ClusterInfo:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespaces: Dict[str, NamespaceInfo] = {}
        self.revocable_nodes: Dict[str, NodeInfo] = {}
        self.node_list: List[str] = []
        # incremental steady-state cycle (docs/design/incremental_cycle.md):
        # populated only by SchedulerCache's persistent-snapshot path.
        # incr_mode: None (legacy full rebuild), "full" (periodic/forced
        # rebuild of the persistent snapshot) or "incremental" (patched in
        # place); patched_* name exactly the entities re-cloned this cycle
        # (the session/solver's invalidation surface); the aux fields are
        # maintained per patch so open_session's O(jobs+nodes) rollups
        # become O(dirty).
        self.incr_mode = None
        self.incr_seq: int = 0
        self.patched_jobs = None        # set[str] | None
        self.patched_nodes = None       # set[str] | None
        self.quiet: bool = False        # provably-no-op cycle hint
        self.rindex = None              # models.arrays.ResourceIndex
        self.total_resource = None      # Resource (sum of node allocatable)
        self.pg_fprints = None          # {job uid: status_fingerprint}
        self.pending_task_jobs = None   # {uid: job has Pending tasks}
        self.pending_phase_jobs = None  # {uid: PodGroup phase == Pending}

    def __repr__(self):
        return (f"ClusterInfo(jobs={len(self.jobs)}, nodes={len(self.nodes)}, "
                f"queues={len(self.queues)})")
