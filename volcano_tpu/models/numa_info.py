"""NUMA topology info (reference: pkg/scheduler/api/numa_info.go:38-185).

Per-node NUMA/CPU detail ingested from the Numatopology CRD: per-resource
allocatable sets, cpu detail (numa/socket/core ids), topology policies, and
the Allocate/Release set operations used by the numaaware plugin's event
handlers.
"""

from __future__ import annotations

from typing import Dict, Set

from .objects import CpuInfo, Numatopology


class ResourceInfo:
    def __init__(self, allocatable: Set[int] = None, capacity: int = 0):
        self.allocatable: Set[int] = set(allocatable or ())
        self.capacity = capacity

    def clone(self) -> "ResourceInfo":
        return ResourceInfo(set(self.allocatable), self.capacity)


class NumatopoInfo:
    def __init__(self, name: str = ""):
        self.name = name
        self.policies: Dict[str, str] = {}
        self.numa_res_map: Dict[str, ResourceInfo] = {}
        self.cpu_detail: Dict[int, CpuInfo] = {}
        self.res_reserved: Dict[str, float] = {}

    @classmethod
    def from_crd(cls, nt: Numatopology) -> "NumatopoInfo":
        info = cls(nt.metadata.name)
        info.policies = dict(nt.policies)
        for res, ri in nt.numa_res.items():
            info.numa_res_map[res] = ResourceInfo(set(ri.allocatable), ri.capacity)
        info.cpu_detail = dict(nt.cpu_detail)
        return info

    def clone(self) -> "NumatopoInfo":
        c = NumatopoInfo(self.name)
        c.policies = dict(self.policies)
        c.numa_res_map = {k: v.clone() for k, v in self.numa_res_map.items()}
        c.cpu_detail = dict(self.cpu_detail)
        c.res_reserved = dict(self.res_reserved)
        return c

    # ResNumaSets ops (numa_info.go:150-185): the scheduler-side view takes
    # sets out on allocate and returns them on release.
    def allocate(self, res_sets: Dict[str, Set[int]]) -> None:
        for res, taken in res_sets.items():
            ri = self.numa_res_map.get(res)
            if ri is not None:
                ri.allocatable -= set(taken)

    def release(self, res_sets: Dict[str, Set[int]]) -> None:
        for res, returned in res_sets.items():
            ri = self.numa_res_map.get(res)
            if ri is not None:
                ri.allocatable |= set(returned)
