"""TaskInfo / JobInfo: scheduler-facing wrappers over Pod and PodGroup.

Behavioral contract mirrors the reference (pkg/scheduler/api/job_info.go):
status taxonomy (job_info.go / types.go:26-74), readiness accounting
(ReadyTaskNum:509, WaitingTaskNum:531, ValidTaskNum:572,
CheckTaskMinAvailable:543, Ready:587), and annotation extraction
(preemptable:304, revocable zone:332, sla waiting time:286, budget:354).
"""

from __future__ import annotations

import enum
from collections import defaultdict
from typing import Dict, List, Optional

from ..utils.fastclone import fast_clone
from . import objects
from .objects import Pod, PodGroup
from .resource import Resource
from .unschedule_info import FitErrors


class TaskStatus(enum.IntFlag):
    """Task status bits (reference: pkg/scheduler/api/types.go:26-74)."""
    Pending = 1 << 0
    Allocated = 1 << 1
    Pipelined = 1 << 2
    Binding = 1 << 3
    Bound = 1 << 4
    Running = 1 << 5
    Releasing = 1 << 6
    Succeeded = 1 << 7
    Failed = 1 << 8
    Unknown = 1 << 9


_ALLOCATED_STATUSES = frozenset((TaskStatus.Bound, TaskStatus.Binding,
                                 TaskStatus.Running, TaskStatus.Allocated))


def allocated_status(status: TaskStatus) -> bool:
    """Statuses that occupy node resources from the scheduler's viewpoint
    (reference: pkg/scheduler/api/job_info.go AllocatedStatus)."""
    return status in _ALLOCATED_STATUSES


def is_terminated(status: TaskStatus) -> bool:
    return status in (TaskStatus.Succeeded, TaskStatus.Failed)


def get_task_status(pod: Pod) -> TaskStatus:
    """Pod phase -> TaskStatus (reference: pkg/scheduler/api/pod_info.go)."""
    phase = pod.status.phase
    if phase == "Running":
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.Releasing
        return TaskStatus.Running
    if phase == "Pending":
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.Releasing
        if pod.spec.node_name:
            return TaskStatus.Bound
        return TaskStatus.Pending
    if phase == "Succeeded":
        return TaskStatus.Succeeded
    if phase == "Failed":
        return TaskStatus.Failed
    return TaskStatus.Unknown


def get_job_id(pod: Pod) -> str:
    """PodGroup link via annotation (reference: job_info.go:99-106)."""
    gn = pod.metadata.annotations.get(objects.GROUP_NAME_ANNOTATION, "")
    if gn:
        return f"{pod.metadata.namespace}/{gn}"
    return ""


def get_task_id(pod: Pod) -> str:
    return pod.metadata.annotations.get(objects.TASK_SPEC_KEY, "")


class TaskInfo:
    """Scheduler view of one Pod (reference: job_info.go:70-147)."""

    __slots__ = ("uid", "job", "name", "namespace", "resreq", "init_resreq",
                 "node_name", "status", "priority", "volume_ready",
                 "preemptable", "revocable_zone", "topology_policy", "pod",
                 "best_effort", "last_transaction", "pod_volumes",
                 "constraint_key_cache", "req_key_cache",
                 "group_sig_cache", "has_volumes", "key_cache")

    def __init__(self, pod: Pod):
        req = pod.resource_request()
        self.uid: str = pod.metadata.uid or pod.metadata.key()
        self.job: str = get_job_id(pod)
        self.name: str = pod.metadata.name
        self.namespace: str = pod.metadata.namespace
        # "ns/name" precomputed once: the bind flush reads it ~4x per pod
        # (ledger stamps, node task tables, the native echo/apply passes),
        # and a fresh f-string re-hashes on every dict probe while this
        # one's hash is cached after first use
        self.key_cache: str = f"{self.namespace}/{self.name}"
        self.init_resreq: Resource = req
        self.resreq: Resource = req.clone()
        self.node_name: str = pod.spec.node_name
        self.status: TaskStatus = get_task_status(pod)
        self.priority: int = pod.spec.priority if pod.spec.priority is not None else 1
        self.volume_ready: bool = False
        pa = pod.metadata.annotations.get(objects.PREEMPTABLE_KEY)
        self.preemptable: bool = str(pa).lower() == "true" if pa is not None else False
        self.revocable_zone: str = pod.metadata.annotations.get(objects.REVOCABLE_ZONE_KEY, "")
        self.topology_policy: str = pod.metadata.annotations.get(objects.NUMA_TOPOLOGY_POLICY_KEY, "")
        self.pod: Pod = pod
        self.best_effort: bool = self.init_resreq.is_empty()
        self.last_transaction = None
        self.pod_volumes = None
        # lazy scheduling-constraint / request fingerprints (models/arrays.py
        # grouping); pod constraints and resreq are immutable, so clones
        # inherit them
        self.constraint_key_cache = None
        self.req_key_cache = None
        self.group_sig_cache = None
        self.has_volumes = bool(pod.spec.volumes)

    @property
    def task_id(self) -> str:
        return get_task_id(self.pod)

    def clone(self) -> "TaskInfo":
        c = TaskInfo.__new__(TaskInfo)
        c.uid = self.uid
        c.job = self.job
        c.name = self.name
        c.namespace = self.namespace
        # resreq/init_resreq are immutable after construction (nothing in
        # the scheduler mutates a task's request in place — a changed pod
        # spec arrives as a *new* TaskInfo via the event handlers), so
        # clones share them; a cycle clones every task 3+ times and the
        # defensive Resource copies dominated snapshot cost
        c.resreq = self.resreq
        c.init_resreq = self.init_resreq
        c.node_name = self.node_name
        c.status = self.status
        c.priority = self.priority
        c.volume_ready = self.volume_ready
        c.preemptable = self.preemptable
        c.revocable_zone = self.revocable_zone
        c.topology_policy = self.topology_policy
        c.pod = self.pod
        c.best_effort = self.best_effort
        c.last_transaction = self.last_transaction
        c.pod_volumes = self.pod_volumes
        c.constraint_key_cache = self.constraint_key_cache
        c.req_key_cache = self.req_key_cache
        c.group_sig_cache = self.group_sig_cache
        c.has_volumes = self.has_volumes
        c.key_cache = self.key_cache
        return c

    def key(self) -> str:
        return self.key_cache

    def __repr__(self):
        return (f"Task ({self.uid}:{self.namespace}/{self.name}): "
                f"job {self.job}, status {self.status.name}, pri {self.priority}")


_fm_cache = None
_fm_tried = False


def _fastmodel():
    """Lazy handle to the native snapshot accelerators (None = fallback)."""
    global _fm_cache, _fm_tried
    if not _fm_tried:
        _fm_tried = True
        try:
            from ..native.build import fastmodel
            mod = fastmodel()
            if mod is not None:
                mod.register_task_type(TaskInfo)
                mod.register_resource_type(Resource)
                if hasattr(mod, "register_task_status"):
                    # the bind-echo pass needs the enum members + the
                    # allocated set to evaluate its guards natively
                    mod.register_task_status(TaskStatus,
                                             _ALLOCATED_STATUSES)
                _fm_cache = mod
        except Exception:
            _fm_cache = None
    return _fm_cache


class DisruptionBudget:
    """Job disruption budget (reference: job_info.go:38-58)."""

    def __init__(self, min_available: str = "", max_unavailable: str = ""):
        self.min_available = min_available
        self.max_unavailable = max_unavailable

    def clone(self) -> "DisruptionBudget":
        return DisruptionBudget(self.min_available, self.max_unavailable)


class JobInfo:
    """Scheduler view of one PodGroup and its tasks
    (reference: job_info.go:187-591)."""

    def __init__(self, uid: str, *tasks: TaskInfo, clock=None):
        self.uid: str = uid
        self.name: str = ""
        self.namespace: str = ""
        self.queue: str = objects.DEFAULT_QUEUE
        self.priority: int = 0
        self.min_available: int = 0
        self.waiting_time: Optional[float] = None   # sla-waiting-time seconds
        self.job_fit_errors: str = ""
        self.nodes_fit_errors: Dict[str, FitErrors] = {}
        self.tasks: Dict[str, TaskInfo] = {}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = defaultdict(dict)
        self.allocated: Resource = Resource()
        self.total_request: Resource = Resource()
        # running sum of Pending tasks' requests (proportion's queue
        # `request` walk was one Resource.add per pending task per cycle —
        # 50k adds at the burst benchmark)
        self.pending_request: Resource = Resource()
        self.creation_timestamp: float = 0.0
        self.pod_group: Optional[PodGroup] = None
        # copy-on-write marker: snapshot clones share the cache's PodGroup
        # until a session-side mutation claims it (own_pod_group)
        self.pod_group_owned: bool = True
        # stamped when the cache first sees the job, so the reservation
        # election's "longest waiting" survives per-cycle snapshot clones
        # (clone() copies it; the reference's ScheduleStartTimestamp
        # analogue). The cache passes its store's clock so the stamp
        # shares the session timebase — virtual under the churn simulator
        import time as _t
        self.scheduling_start_time: float = \
            clock.now() if clock is not None else _t.time()
        self.preemptable: bool = False
        self.revocable_zone: str = ""
        self.budget: DisruptionBudget = DisruptionBudget()
        self.task_min_available: Dict[str, int] = {}
        self.task_min_available_total: int = 0
        # status-index version: bumped on any task/status mutation so the
        # readiness counters can memoize (preempt calls ready_task_num
        # tens of thousands of times between mutations)
        self._status_version: int = 0
        self._ready_cache: tuple = (-1, 0)
        # session-scope deferred-apply deltas (Session.materialize):
        # placements recorded by the allocate action whose object-model
        # apply (status moves, node accounting) has not run yet. Readiness
        # and status rollups stay exact by adding the deltas.
        self.deferred_alloc: int = 0
        self.deferred_pipe: int = 0
        for t in tasks:
            self.add_task_info(t)

    # -- podgroup ingestion ------------------------------------------------

    def set_pod_group(self, pg: PodGroup) -> None:
        self.name = pg.metadata.name
        self.namespace = pg.metadata.namespace
        self.min_available = pg.spec.min_member
        self.queue = pg.spec.queue
        self.creation_timestamp = pg.metadata.creation_timestamp
        self.waiting_time = self._extract_waiting_time(pg)
        self.preemptable = self._extract_preemptable(pg)
        self.revocable_zone = self._extract_revocable_zone(pg)
        self.budget = self._extract_budget(pg)
        self.task_min_available = dict(pg.spec.min_task_member)
        self.task_min_available_total = sum(self.task_min_available.values())
        self.pod_group = pg
        self.pod_group_owned = True

    def unset_pod_group(self) -> None:
        self.pod_group = None

    def own_pod_group(self) -> Optional[PodGroup]:
        """Claim a private PodGroup copy before a session-side mutation
        (copy-on-write counterpart of clone()); writeback goes through the
        status updater, never through the cache's shared object."""
        if not self.pod_group_owned and self.pod_group is not None:
            self.pod_group = fast_clone(self.pod_group)
            self.pod_group_owned = True
        return self.pod_group

    @staticmethod
    def _extract_waiting_time(pg: PodGroup) -> Optional[float]:
        """Invalid annotations are treated as unset, never fatal
        (reference: job_info.go:286-300 logs and returns nil)."""
        v = pg.metadata.annotations.get(objects.SLA_WAITING_TIME_KEY)
        if v is None:
            return None
        w = parse_duration(v)
        if w is None or w <= 0:
            return None
        return w

    @staticmethod
    def _extract_preemptable(pg: PodGroup) -> bool:
        """Annotations beat labels (reference: job_info.go:304-330)."""
        for src in (pg.metadata.annotations, pg.metadata.labels):
            if objects.PREEMPTABLE_KEY in src:
                return str(src[objects.PREEMPTABLE_KEY]).lower() == "true"
        return False

    @staticmethod
    def _extract_revocable_zone(pg: PodGroup) -> str:
        v = pg.metadata.annotations.get(objects.REVOCABLE_ZONE_KEY)
        if v is not None:
            return v if v == "*" else ""
        if pg.metadata.annotations.get(objects.PREEMPTABLE_KEY, "").lower() == "true":
            return "*"
        return ""

    @staticmethod
    def _extract_budget(pg: PodGroup) -> DisruptionBudget:
        a = pg.metadata.annotations
        if objects.JDB_MIN_AVAILABLE_KEY in a:
            return DisruptionBudget(min_available=a[objects.JDB_MIN_AVAILABLE_KEY])
        if objects.JDB_MAX_UNAVAILABLE_KEY in a:
            return DisruptionBudget(max_unavailable=a[objects.JDB_MAX_UNAVAILABLE_KEY])
        return DisruptionBudget()

    def get_min_resources(self) -> Resource:
        if self.pod_group is None or self.pod_group.spec.min_resources is None:
            return Resource()
        return Resource.from_resource_list(self.pod_group.spec.min_resources)

    # -- task management ---------------------------------------------------

    def add_task_info(self, ti: TaskInfo) -> None:
        self._status_version += 1
        self.tasks[ti.uid] = ti
        self.task_status_index[ti.status][ti.uid] = ti
        if allocated_status(ti.status):
            self.allocated.add(ti.resreq)
        elif ti.status == TaskStatus.Pending:
            self.pending_request.add(ti.resreq)
        self.total_request.add(ti.resreq)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        self.delete_task_info(task)
        task.status = status
        self.add_task_info(task)

    def move_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """In-place status move for a task already registered in this job.

        Equivalent to :meth:`update_task_status` but skips the net-zero
        total_request sub/add pair and only touches ``allocated`` when the
        allocated-ness actually flips — the hot allocate/bind path moves
        every placed task three times per cycle, so the saved Resource
        arithmetic is significant at 50k tasks."""
        stored = self.tasks.get(task.uid)
        if stored is None:
            raise KeyError(f"failed to find task <{task.namespace}/"
                           f"{task.name}> in job <{self.namespace}/{self.name}>")
        self._status_version += 1
        old = stored.status
        idx = self.task_status_index[old]
        idx.pop(task.uid, None)
        if not idx:
            del self.task_status_index[old]
        was, now = allocated_status(old), allocated_status(status)
        if was and not now:
            self.allocated.sub(stored.resreq)
        elif now and not was:
            self.allocated.add(stored.resreq)
        if old == TaskStatus.Pending and status != TaskStatus.Pending:
            self.pending_request.sub(stored.resreq)
        elif status == TaskStatus.Pending and old != TaskStatus.Pending:
            self.pending_request.add(stored.resreq)
        task.status = status
        self.tasks[task.uid] = task
        self.task_status_index[status][task.uid] = task

    def move_tasks_status_bulk(self, tasks: List[TaskInfo],
                               status: TaskStatus) -> Optional[Resource]:
        """:meth:`move_task_status` over many registered tasks with the
        allocated-resource flips accumulated into one Resource op pair and
        a single index-version bump. Raises before any mutation if a task
        is unknown (the bulk callers stage whole gangs all-or-nothing)."""
        stored_list = []
        for task in tasks:
            stored = self.tasks.get(task.uid)
            if stored is None:
                raise KeyError(f"failed to find task <{task.namespace}/"
                               f"{task.name}> in job "
                               f"<{self.namespace}/{self.name}>")
            stored_list.append(stored)
        self._status_version += 1
        now = allocated_status(status)
        now_pending = status == TaskStatus.Pending
        flip_add = None
        flip_sub = None
        pend_add = None
        pend_sub = None
        new_idx = self.task_status_index[status]
        for task, stored in zip(tasks, stored_list):
            old = stored.status
            idx = self.task_status_index[old]
            idx.pop(task.uid, None)
            if not idx and old != status:   # never drop the target index
                del self.task_status_index[old]
            was = allocated_status(old)
            if was and not now:
                if flip_sub is None:
                    flip_sub = Resource()
                flip_sub.add(stored.resreq)
            elif now and not was:
                if flip_add is None:
                    flip_add = Resource()
                flip_add.add(stored.resreq)
            was_pending = old == TaskStatus.Pending
            if was_pending and not now_pending:
                if pend_sub is None:
                    pend_sub = Resource()
                pend_sub.add(stored.resreq)
            elif now_pending and not was_pending:
                if pend_add is None:
                    pend_add = Resource()
                pend_add.add(stored.resreq)
            task.status = status
            self.tasks[task.uid] = task
            new_idx[task.uid] = task
        if flip_add is not None:
            self.allocated.add(flip_add)
        if flip_sub is not None:
            self.allocated.sub(flip_sub)
        if pend_add is not None:
            self.pending_request.add(pend_add)
        if pend_sub is not None:
            self.pending_request.sub(pend_sub)
        return flip_add

    def delete_task_info(self, ti: TaskInfo) -> None:
        self._status_version += 1
        task = self.tasks.get(ti.uid)
        if task is None:
            raise KeyError(f"failed to find task <{ti.namespace}/{ti.name}> "
                           f"in job <{self.namespace}/{self.name}>")
        if allocated_status(task.status):
            self.allocated.sub(task.resreq)
        elif task.status == TaskStatus.Pending:
            self.pending_request.sub(task.resreq)
        self.total_request.sub(task.resreq)
        del self.tasks[task.uid]
        idx = self.task_status_index[task.status]
        idx.pop(task.uid, None)
        if not idx:
            del self.task_status_index[task.status]

    def clone(self) -> "JobInfo":
        fm = _fastmodel()
        if fm is not None:
            c = self._clone_native(fm)
            if c is not None:
                return c
        return self._clone_python()

    def _clone_native(self, fm) -> Optional["JobInfo"]:
        """C fast path: one __dict__ shell copy + the fields that need
        fresh values — exactly the set the Python clone resets. Returns
        None (caller falls back) for subclassed task tables."""
        try:
            tasks, plain = fm.clone_task_table(self.tasks)
        except TypeError:
            return None
        info = fm.shell_clone(self)
        info.job_fit_errors = ""
        info._status_version = 0
        info._ready_cache = (-1, 0)
        info.deferred_alloc = 0
        info.deferred_pipe = 0
        info.nodes_fit_errors = {}
        info.pod_group_owned = False   # COW PodGroup (see _clone_python)
        info.budget = self.budget.clone()
        info.task_min_available = dict(self.task_min_available)
        index = defaultdict(dict)
        index.update(plain)
        info.tasks = tasks
        info.task_status_index = index
        info.allocated = fm.clone_resource(self.allocated)
        info.total_request = fm.clone_resource(self.total_request)
        info.pending_request = fm.clone_resource(self.pending_request)
        return info

    def _clone_python(self) -> "JobInfo":
        # __new__ + explicit fields: JobInfo() runs the full constructor
        # (time.time(), defaultdicts, ~25 defaults) only for clone() to
        # overwrite nearly all of it — measurable at 6k jobs per snapshot
        info = JobInfo.__new__(JobInfo)
        info.uid = self.uid
        info.job_fit_errors = ""
        info._status_version = 0
        info._ready_cache = (-1, 0)
        info.deferred_alloc = 0
        info.deferred_pipe = 0
        info.name = self.name
        info.namespace = self.namespace
        info.queue = self.queue
        info.priority = self.priority
        info.min_available = self.min_available
        info.waiting_time = self.waiting_time
        info.nodes_fit_errors = {}
        # copy-on-write PodGroup: the snapshot shares the cache's object
        # until a session-side mutation (enqueue phase flip, condition or
        # status write) claims a private copy via own_pod_group() — most
        # jobs per cycle are never mutated, and the deep copy dominated
        # snapshot cost (reference pays it via cache.go:793 deepcopy)
        info.pod_group = self.pod_group
        info.pod_group_owned = False
        info.creation_timestamp = self.creation_timestamp
        info.scheduling_start_time = self.scheduling_start_time
        info.preemptable = self.preemptable
        info.revocable_zone = self.revocable_zone
        info.budget = self.budget.clone()
        info.task_min_available = dict(self.task_min_available)
        info.task_min_available_total = self.task_min_available_total
        # direct task copy: the status index and allocated/total aggregates
        # are cloned rather than re-derived one add_task_info at a time —
        # at 50k tasks the replay's per-task Resource arithmetic dominated
        # the snapshot (cache.go:827-876 pays the same via deepcopy-gen).
        # The C fast path (native/fastmodel.c) does the verbatim slot
        # copies + index build in one pass; exact-type tables only.
        tasks = None
        fm = _fastmodel()
        if fm is not None:
            try:
                tasks, plain = fm.clone_task_table(self.tasks)
                index = defaultdict(dict)
                index.update(plain)
            except TypeError:     # subclassed tasks: python fallback
                tasks = None
        if tasks is None:
            tasks = {}
            index = defaultdict(dict)
            for uid, task in self.tasks.items():
                c = task.clone()
                tasks[uid] = c
                index[c.status][uid] = c
        info.tasks = tasks
        info.task_status_index = index
        info.allocated = self.allocated.clone()
        info.total_request = self.total_request.clone()
        info.pending_request = self.pending_request.clone()
        return info

    # -- readiness accounting ---------------------------------------------

    def ready_task_num(self) -> int:
        """Allocated-ish + Succeeded + best-effort Pending
        (reference: job_info.go:509-527). Memoized per status version."""
        cached_version, cached = self._ready_cache
        if cached_version == self._status_version:
            return cached + self.deferred_alloc
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if allocated_status(status) or status == TaskStatus.Succeeded:
                occupied += len(tasks)
            elif status == TaskStatus.Pending:
                occupied += sum(1 for t in tasks.values() if t.init_resreq.is_empty())
        self._ready_cache = (self._status_version, occupied)
        return occupied + self.deferred_alloc

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.Pipelined, {})) \
            + self.deferred_pipe

    def valid_task_num(self) -> int:
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if (allocated_status(status) or status == TaskStatus.Succeeded
                    or status == TaskStatus.Pipelined or status == TaskStatus.Pending):
                occupied += len(tasks)
        return occupied

    def check_task_min_available(self) -> bool:
        """Per-task-type minAvailable check (reference: job_info.go:543-569)."""
        if not self.task_min_available:
            return True   # no per-type minimums: skip the status sweep
        if self.min_available < self.task_min_available_total:
            return True
        actual: Dict[str, int] = defaultdict(int)
        for status, tasks in self.task_status_index.items():
            if (allocated_status(status) or status == TaskStatus.Succeeded
                    or status == TaskStatus.Pipelined or status == TaskStatus.Pending):
                for t in tasks.values():
                    actual[t.task_id] += 1
        return all(actual.get(name, 0) >= need
                   for name, need in self.task_min_available.items())

    def ready(self) -> bool:
        return self.ready_task_num() >= self.min_available

    def is_pending(self) -> bool:
        return (self.pod_group is None
                or self.pod_group.status.phase == objects.PodGroupPhase.PENDING)

    def fit_error(self) -> str:
        """Histogram of pending/fit reasons (reference: job_info.go:487-505)."""
        reasons: Dict[str, int] = defaultdict(int)
        for status, tasks in self.task_status_index.items():
            reasons[status.name] += len(tasks)
        sorted_reasons = sorted(reasons.items(), key=lambda kv: kv[0])
        msg = ", ".join(f"{n} {r}" for r, n in sorted_reasons)
        return f"pod group is not ready, {self.min_available} minAvailable, {msg}"

    def __repr__(self):
        return (f"Job ({self.uid}): namespace {self.namespace} ({self.name}), "
                f"minAvailable {self.min_available}")


def parse_duration(v: str) -> Optional[float]:
    """Go-style duration string to seconds ("1h30m", "300s", "1.5h")."""
    import re
    if v is None:
        return None
    v = str(v).strip()
    m = re.findall(r"([0-9]*\.?[0-9]+)(ms|us|ns|h|m|s)", v)
    if not m:
        try:
            return float(v)
        except ValueError:
            return None
    mult = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}
    return sum(float(num) * mult[unit] for num, unit in m)
