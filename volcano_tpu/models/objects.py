"""Standalone API objects: the framework's equivalents of the reference's CRD
groups and the slice of core/v1 it consumes.

The reference defines four CRD groups over the Kubernetes API server
(reference: vendor/volcano.sh/apis/pkg/apis/{batch,scheduling,bus,nodeinfo}).
This framework is standalone, so the same object shapes live here as plain
dataclasses and are stored/watched via :mod:`volcano_tpu.apiserver`.

Object groups:
  * core: ObjectMeta, Pod, Node, PriorityClass (the slice of core/v1 used)
  * scheduling: PodGroup, Queue            (scheduling/v1beta1)
  * batch: Job (+TaskSpec/LifecyclePolicy) (batch/v1alpha1)
  * bus: Command, actions & events         (bus/v1alpha1)
  * nodeinfo: Numatopology                 (nodeinfo/v1alpha1)
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .resource import Resource

# ---------------------------------------------------------------------------
# Annotation / label keys (reference: scheduling/v1beta1 & batch/v1alpha1 consts)
# ---------------------------------------------------------------------------

GROUP_NAME_ANNOTATION = "scheduling.k8s.io/group-name"       # pod -> PodGroup link
TASK_SPEC_KEY = "volcano.sh/task-spec"                       # pod -> task name in Job
JOB_NAME_KEY = "volcano.sh/job-name"
JOB_VERSION_KEY = "volcano.sh/job-version"
QUEUE_NAME_KEY = "volcano.sh/queue-name"
PREEMPTABLE_KEY = "volcano.sh/preemptable"
REVOCABLE_ZONE_KEY = "volcano.sh/revocable-zone"
JDB_MIN_AVAILABLE_KEY = "volcano.sh/jdb-min-available"
JDB_MAX_UNAVAILABLE_KEY = "volcano.sh/jdb-max-unavailable"
SLA_WAITING_TIME_KEY = "sla-waiting-time"
TOPOLOGY_AFFINITY_KEY = "volcano.sh/task-topology-affinity"
TOPOLOGY_ANTI_AFFINITY_KEY = "volcano.sh/task-topology-anti-affinity"
TOPOLOGY_TASK_ORDER_KEY = "volcano.sh/task-topology-task-order"
NUMA_TOPOLOGY_POLICY_KEY = "volcano.sh/numa-topology-policy"
QUEUE_HIERARCHY_ANNOTATION = "volcano.sh/hierarchy"
QUEUE_HIERARCHY_WEIGHT_ANNOTATION = "volcano.sh/hierarchy-weights"
OVERSUBSCRIPTION_NODE_KEY = "volcano.sh/oversubscription"
OVERSUBSCRIPTION_RESOURCE_KEY = "volcano.sh/oversubscription-resource"
OFFLINE_JOB_EVICTING_KEY = "volcano.sh/offline-job-evicting"
REVOCABLE_ZONE_LABEL = "volcano.sh/revocable-zone"

DEFAULT_SCHEDULER_NAME = "volcano"
DEFAULT_QUEUE = "default"

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_uid_counter):08d}"


# ---------------------------------------------------------------------------
# core/v1 slice
# ---------------------------------------------------------------------------

@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    resource_version: int = 0
    deletion_timestamp: Optional[float] = None
    owner: Optional[str] = None  # "kind/namespace/name" of the controller owner

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"      # Equal | Exists
    value: str = ""
    effect: str = ""             # "" matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"   # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"         # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        has = self.key in labels
        val = labels.get(self.key)
        if self.operator == "In":
            return has and val in self.values
        if self.operator == "NotIn":
            # k8s label-selector semantics: absent keys satisfy NotIn
            return (not has) or val not in self.values
        if self.operator == "Exists":
            return has
        if self.operator == "DoesNotExist":
            return not has
        if self.operator == "Gt":
            try:
                return has and float(val) > float(self.values[0])
            except (ValueError, IndexError):
                return False
        if self.operator == "Lt":
            try:
                return has and float(val) < float(self.values[0])
            except (ValueError, IndexError):
                return False
        return False


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    required: List[NodeSelectorTerm] = field(default_factory=list)      # OR of terms
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    label_selector: List[NodeSelectorRequirement] = field(default_factory=list)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None


@dataclass
class TopologySpreadConstraint:
    """PodTopologySpread slice (k8s topologySpreadConstraints): spread the
    selected pods across the values of a node topology label, bounding the
    count difference between the most- and least-loaded topology by
    ``max_skew``. An empty ``label_selector`` selects the pod's OWN job
    siblings (the volcano gang case — the scheduler fills it from the
    job's pods)."""
    max_skew: int = 1
    topology_key: str = "topology.kubernetes.io/zone"
    # DoNotSchedule (hard, lowered into the kernel mask) |
    # ScheduleAnyway (soft, lowered into the additive score)
    when_unsatisfiable: str = "DoNotSchedule"
    label_selector: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    requests: Dict[str, Any] = field(default_factory=dict)   # resource list
    limits: Dict[str, Any] = field(default_factory=dict)
    ports: List[int] = field(default_factory=list)
    command: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    volume_mounts: List[Dict[str, str]] = field(default_factory=list)


@dataclass
class PodSpec:
    """Pod spec slice.

    Immutability contract (matches k8s: a pod's spec is immutable after
    creation except the binding): once a pod has been stored,
    ``containers``/``init_containers``/``affinity``/``volumes`` are never
    mutated in place — the job controller and its svc/ssh/env plugins edit
    them only on freshly built pods BEFORE ``store.create``. Clones share
    these substructures (see the specialized cloner below).
    ``node_selector``/``tolerations`` ARE extended in place by pod admission
    mutators (webhooks/pods.py), so clones copy those containers (the
    Toleration elements themselves are immutable and shared)."""

    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    # immutable-after-store like affinity (clones share the list)
    topology_spread: List[TopologySpreadConstraint] = field(
        default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    priority: Optional[int] = None
    priority_class_name: str = ""
    restart_policy: str = "OnFailure"
    host_ports: List[int] = field(default_factory=list)
    volumes: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class PodStatus:
    phase: str = "Pending"   # Pending | Running | Succeeded | Failed | Unknown
    reason: str = ""
    message: str = ""
    host_ip: str = ""
    exit_code: Optional[int] = None  # terminated main-container exit code


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def resource_request(self) -> Resource:
        """Aggregate container requests; init containers contribute their max
        per dimension (k8s pod resource semantics used by NewTaskInfo,
        reference: pkg/scheduler/api/pod_info.go GetPodResourceRequest).

        Memoized on the pod and treated as immutable: containers never
        change after storage (PodSpec contract), every TaskInfo rebuild of
        the same pod — ingest, bind echo, resync — re-parses the same
        quantities, and the parse dominated the 50k-bind watch-echo path.
        Clones share the cached Resource."""
        rr = self.__dict__.get("_rr")
        if rr is None:
            rr = Resource()
            for c in self.spec.containers:
                rr.add(Resource.from_resource_list(c.requests))
            for c in self.spec.init_containers:
                rr.set_max_resource(Resource.from_resource_list(c.requests))
            self.__dict__["_rr"] = rr
        return rr


@dataclass
class NodeStatus:
    allocatable: Dict[str, Any] = field(default_factory=dict)
    capacity: Dict[str, Any] = field(default_factory=dict)
    ready: bool = True


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"


@dataclass
class ResourceQuota:
    """Consumed only for namespace weight (reference: namespace_info.go)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# scheduling group: PodGroup & Queue
# ---------------------------------------------------------------------------

class PodGroupPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"
    COMPLETED = "Completed"


class PodGroupConditionType:
    UNSCHEDULABLE = "Unschedulable"
    SCHEDULED = "Scheduled"


NOT_ENOUGH_RESOURCES_REASON = "NotEnoughResources"
NOT_ENOUGH_PODS_REASON = "NotEnoughTasks"
POD_GROUP_READY = "tasks in gang are ready to be scheduled"
POD_GROUP_NOT_READY = "pod group is not ready"


@dataclass
class PodGroupCondition:
    type: str = ""
    status: str = "True"
    transition_id: str = ""
    last_transition_time: float = 0.0
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupSpec:
    min_member: int = 0
    min_task_member: Dict[str, int] = field(default_factory=dict)
    queue: str = DEFAULT_QUEUE
    priority_class_name: str = ""
    min_resources: Optional[Dict[str, Any]] = None


@dataclass
class PodGroupStatus:
    phase: str = PodGroupPhase.PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


class QueueState:
    OPEN = "Open"
    CLOSED = "Closed"
    CLOSING = "Closing"
    UNKNOWN = "Unknown"


@dataclass
class QueueSpec:
    weight: int = 1
    capability: Optional[Dict[str, Any]] = None
    reclaimable: bool = True
    guarantee: Optional[Dict[str, Any]] = None
    extend_clusters: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class QueueStatus:
    state: str = QueueState.OPEN
    unknown: int = 0
    pending: int = 0
    running: int = 0
    inqueue: int = 0


@dataclass
class Queue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: QueueSpec = field(default_factory=QueueSpec)
    status: QueueStatus = field(default_factory=QueueStatus)


# ---------------------------------------------------------------------------
# batch group: Job
# ---------------------------------------------------------------------------

class JobPhase:
    PENDING = "Pending"
    ABORTING = "Aborting"
    ABORTED = "Aborted"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    COMPLETING = "Completing"
    COMPLETED = "Completed"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"
    FAILED = "Failed"


class JobEvent:
    """Lifecycle events (reference: vendor/.../bus/v1alpha1/events.go)."""
    ANY = "*"
    POD_FAILED = "PodFailed"
    POD_EVICTED = "PodEvicted"
    UNSCHEDULABLE = "Unschedulable"
    POD_PENDING = "PodPending"
    TASK_COMPLETED = "TaskCompleted"
    TASK_FAILED = "TaskFailed"
    JOB_UNKNOWN = "JobUnknown"
    OUT_OF_SYNC = "OutOfSync"
    COMMAND_ISSUED = "CommandIssued"
    JOB_UPDATED = "JobUpdated"


class JobAction:
    """Lifecycle actions (reference: vendor/.../bus/v1alpha1/actions.go:20-50)."""
    ABORT_JOB = "AbortJob"
    RESTART_JOB = "RestartJob"
    RESTART_TASK = "RestartTask"
    TERMINATE_JOB = "TerminateJob"
    COMPLETE_JOB = "CompleteJob"
    RESUME_JOB = "ResumeJob"
    SYNC_JOB = "SyncJob"
    ENQUEUE_JOB = "EnqueueJob"
    SYNC_QUEUE = "SyncQueue"
    OPEN_QUEUE = "OpenQueue"
    CLOSE_QUEUE = "CloseQueue"


@dataclass
class LifecyclePolicy:
    event: str = ""
    events: List[str] = field(default_factory=list)
    action: str = ""
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None

    def matches(self, event: str, exit_code: Optional[int] = None) -> bool:
        if self.exit_code is not None:
            return exit_code is not None and exit_code == self.exit_code
        evs = set(self.events)
        if self.event:
            evs.add(self.event)
        return event in evs or JobEvent.ANY in evs


@dataclass
class PodTemplate:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class TaskSpec:
    name: str = ""
    replicas: int = 1
    min_available: Optional[int] = None
    template: PodTemplate = field(default_factory=PodTemplate)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    topology_policy: str = ""   # NUMA: none|best-effort|restricted|single-numa-node


@dataclass
class JobSpec:
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    min_available: int = 0
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)  # svc/ssh/env
    queue: str = DEFAULT_QUEUE
    max_retry: int = 3
    ttl_seconds_after_finished: Optional[int] = None
    priority_class_name: str = ""
    min_success: Optional[int] = None


@dataclass
class JobState:
    # empty until the job controller's initiateJob stamps Pending
    # (reference: job_controller_actions.go initJobStatus)
    phase: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class JobStatus:
    state: JobState = field(default_factory=JobState)
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0
    retry_count: int = 0
    min_available: int = 0
    task_status_count: Dict[str, Dict[str, int]] = field(default_factory=dict)
    controlled_resources: Dict[str, str] = field(default_factory=dict)


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


# ---------------------------------------------------------------------------
# core/v1 controlled resources (created by job controller plugins / volumes)
# ---------------------------------------------------------------------------

@dataclass
class Service:
    """Headless service equivalent (created by the svc job plugin,
    reference: pkg/controllers/job/plugins/svc/svc.go:219-264)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = "None"
    ports: List[int] = field(default_factory=list)


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, bytes] = field(default_factory=dict)


@dataclass
class NetworkPolicy:
    """Intra-job network isolation (svc plugin, svc.go:266-313)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_selector: Dict[str, str] = field(default_factory=dict)
    ingress_from_selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: Dict[str, Any] = field(default_factory=dict)
    # binding status (k8s PVC.status + spec.volumeName)
    volume_name: str = ""
    phase: str = "Pending"          # Pending | Bound | Lost

    def requested_bytes(self) -> float:
        from .quantity import parse_quantity
        req = (self.spec.get("resources", {}) or {}).get("requests", {})
        storage = req.get("storage", "0")
        return float(parse_quantity(storage))

    def storage_class(self) -> str:
        return self.spec.get("storageClassName", "") or ""


@dataclass
class PersistentVolume:
    """Cluster-scoped volume (the reference's PV informer feeds the real
    k8s volumebinding plugin, cache/cache.go:84-96; here the store holds
    PVs directly)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: str = "0"             # storage quantity
    storage_class: str = ""
    access_modes: List[str] = field(default_factory=list)
    # node names this PV is reachable from; empty = any node
    node_affinity: List[str] = field(default_factory=list)
    claim_ref: str = ""             # "ns/name" of the bound PVC
    phase: str = "Available"        # Available | Bound | Released

    def capacity_bytes(self) -> float:
        from .quantity import parse_quantity
        return float(parse_quantity(self.capacity))


# ---------------------------------------------------------------------------
# bus group: Command
# ---------------------------------------------------------------------------

@dataclass
class Command:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    action: str = ""
    target_kind: str = "Job"
    target_name: str = ""
    reason: str = ""
    message: str = ""


# ---------------------------------------------------------------------------
# nodeinfo group: Numatopology
# ---------------------------------------------------------------------------

@dataclass
class CpuInfo:
    numa_id: int = 0
    socket_id: int = 0
    core_id: int = 0


@dataclass
class NumaResInfo:
    """Per-resource allocatable set/amount on a node (numatopo_types.go)."""
    allocatable: List[int] = field(default_factory=list)   # e.g. cpu ids
    capacity: int = 0


@dataclass
class Numatopology:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    policies: Dict[str, str] = field(default_factory=dict)  # TopologyManagerPolicy etc.
    numa_res: Dict[str, NumaResInfo] = field(default_factory=dict)
    cpu_detail: Dict[int, CpuInfo] = field(default_factory=dict)
    res_reserved: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# specialized fast_clone cloners for the hot shapes
# ---------------------------------------------------------------------------
# A 50k-bind flush clones every pod several times (store patch + per-watcher
# echo copies); the generic per-attribute recursion over the ~40-object pod
# tree dominated it. These cloners rebuild only the mutable shell and share
# the substructures PodSpec's docstring declares immutable-after-store.

from ..utils.fastclone import register_cloner  # noqa: E402


def _clone_object_meta(m: "ObjectMeta") -> "ObjectMeta":
    new = object.__new__(ObjectMeta)
    d = new.__dict__
    s = m.__dict__
    d.update(s)                        # scalars (str/int/float/None)
    d["labels"] = dict(s["labels"])    # str -> str: shallow copy is exact
    d["annotations"] = dict(s["annotations"])
    return new


def _clone_pod_status(st: "PodStatus") -> "PodStatus":
    new = object.__new__(PodStatus)
    new.__dict__.update(st.__dict__)   # all scalars
    return new


def _clone_pod_spec(sp: "PodSpec") -> "PodSpec":
    new = object.__new__(PodSpec)
    d = new.__dict__
    d.update(sp.__dict__)   # scalars + immutable-after-store subtrees
    #                         (containers/init_containers/affinity/volumes)
    # admission mutators extend these in place on inbound objects, so the
    # containers are copied; the elements are immutable and shared
    d["node_selector"] = dict(sp.node_selector)
    d["tolerations"] = list(sp.tolerations)
    d["host_ports"] = list(sp.host_ports)
    return new


def _clone_pod(p: "Pod") -> "Pod":
    new = object.__new__(Pod)
    d = new.__dict__
    s = p.__dict__
    d["metadata"] = _clone_object_meta(s["metadata"])
    d["spec"] = _clone_pod_spec(s["spec"])
    d["status"] = _clone_pod_status(s["status"])
    rr = s.get("_rr")
    if rr is not None:
        d["_rr"] = rr                  # immutable parse cache: share
    sig = s.get("_sched_group_sig")
    if sig is not None:
        d["_sched_group_sig"] = sig    # encode-group intern id: share
    return new


def clone_pod_for_bind(p: "Pod") -> "Pod":
    """Minimal pod clone for the store's bind patch: only the mutated
    shells (metadata for the resource_version bump, spec for node_name)
    are fresh; labels/annotations/status and every spec subtree are
    SHARED with the stored object. Safe because stored objects are never
    mutated in place (store reads hand out copies; admission mutates
    inbound objects pre-store) — the 50k-bind flush pays two dict.update
    calls per pod instead of a structured deep clone."""
    new = object.__new__(Pod)
    d = new.__dict__
    s = p.__dict__
    m = object.__new__(ObjectMeta)
    m.__dict__.update(s["metadata"].__dict__)   # labels/annotations shared
    d["metadata"] = m
    sp = object.__new__(PodSpec)
    sp.__dict__.update(s["spec"].__dict__)      # subtrees shared
    d["spec"] = sp
    d["status"] = s["status"]                   # shared (bind leaves it)
    rr = s.get("_rr")
    if rr is not None:
        d["_rr"] = rr
    sig = s.get("_sched_group_sig")
    if sig is not None:
        d["_sched_group_sig"] = sig
    return new


def clone_pod_group_for_status(pg: "PodGroup") -> "PodGroup":
    """Minimal podgroup clone for the store's bulk STATUS push: a fresh
    metadata shell (resource_version bump) with the spec SHARED — stored
    objects are never mutated in place, and sharing lets watchers detect
    the status-only echo by spec identity (cache.update_pod_groups_bulk).
    The status is installed by the patch fn, so the clone's own status is
    irrelevant (shared here)."""
    new = object.__new__(PodGroup)
    d = new.__dict__
    s = pg.__dict__
    m = object.__new__(ObjectMeta)
    m.__dict__.update(s["metadata"].__dict__)
    d["metadata"] = m
    d["spec"] = s["spec"]
    d["status"] = s["status"]
    return new


def _clone_pod_group_status(st: "PodGroupStatus") -> "PodGroupStatus":
    new = object.__new__(PodGroupStatus)
    d = new.__dict__
    d.update(st.__dict__)              # phase + counters (scalars)
    # condition entries are replaced/appended, never mutated in place
    # (framework.update_pod_group_condition rebinds conditions[i]), so the
    # elements are shared and only the list is copied
    d["conditions"] = list(st.conditions)
    return new


def _clone_pod_group_spec(sp: "PodGroupSpec") -> "PodGroupSpec":
    # a flat copy (the job controller mutates a gotten pg's spec in place
    # before update, so specs are NOT shareable across clones): scalars +
    # two shallow dict copies with scalar values
    new = object.__new__(PodGroupSpec)
    d = new.__dict__
    d.update(sp.__dict__)
    d["min_task_member"] = dict(sp.min_task_member)
    if sp.min_resources is not None:
        d["min_resources"] = dict(sp.min_resources)
    return new


def _clone_pod_group(pg: "PodGroup") -> "PodGroup":
    """PodGroup clones run once per status-writing job per cycle (the
    copy-on-write claim in JobInfo.own_pod_group) and once per job per
    snapshot echo: rebuild the three shells without generic recursion."""
    new = object.__new__(PodGroup)
    d = new.__dict__
    d["metadata"] = _clone_object_meta(pg.metadata)
    d["spec"] = _clone_pod_group_spec(pg.spec)
    d["status"] = _clone_pod_group_status(pg.status)
    return new


register_cloner(ObjectMeta, _clone_object_meta)
register_cloner(PodStatus, _clone_pod_status)
register_cloner(PodSpec, _clone_pod_spec)
register_cloner(Pod, _clone_pod)
register_cloner(PodGroupStatus, _clone_pod_group_status)
register_cloner(PodGroupSpec, _clone_pod_group_spec)
register_cloner(PodGroup, _clone_pod_group)


def status_fingerprint(status: "PodGroupStatus") -> tuple:
    """Cheap immutable fingerprint of a PodGroup status, used for the
    session-close writeback dedup (framework.JobUpdater) and maintained
    incrementally per patched job by the cache's persistent snapshot
    (docs/design/incremental_cycle.md). The two producers MUST agree
    tuple-for-tuple, which is why the helper lives here rather than in
    either consumer."""
    return (status.phase, status.running, status.succeeded, status.failed,
            tuple((c.type, c.status, c.reason, c.message,
                   c.last_transition_time) for c in status.conditions))
