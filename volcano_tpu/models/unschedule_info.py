"""Fit errors: per task x node failure reasons, aggregated for PodGroup
conditions (reference: pkg/scheduler/api/unschedule_info.go)."""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

ALL_NODE_UNAVAILABLE = "all nodes are unavailable"

# Canonical predicate failure reasons (mirroring upstream k8s strings where
# the reference reuses them).
NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
NODE_AFFINITY_FAILED = "node(s) didn't match Pod's node affinity"
NODE_SELECTOR_FAILED = "node(s) didn't match Pod's node selector"
TAINT_FAILED = "node(s) had taints that the pod didn't tolerate"
NODE_PORT_FAILED = "node(s) didn't have free ports for the requested pod ports"
POD_AFFINITY_FAILED = "node(s) didn't match pod affinity/anti-affinity rules"


class FitError:
    """One task's failure on one node."""

    def __init__(self, task=None, node=None, reasons: Optional[List[str]] = None,
                 task_namespace: str = "", task_name: str = "", node_name: str = ""):
        if task is not None:
            task_namespace, task_name = task.namespace, task.name
        if node is not None:
            node_name = node.name
        self.task_namespace = task_namespace
        self.task_name = task_name
        self.node_name = node_name
        self.reasons: List[str] = list(reasons or [])

    def error(self) -> str:
        return (f"task {self.task_namespace}/{self.task_name} on node "
                f"{self.node_name} fit failed: {', '.join(self.reasons)}")

    def __repr__(self):
        return self.error()


class FitErrors:
    """All nodes' failures for one task (unschedule_info.go)."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self.err: str = ""

    def set_error(self, err: str) -> None:
        self.err = err

    def set_node_error(self, node_name: str, fit_error: FitError) -> None:
        fit_error.node_name = node_name
        self.nodes[node_name] = fit_error

    def error(self) -> str:
        if self.err:
            return self.err
        if not self.nodes:
            return ALL_NODE_UNAVAILABLE
        # histogram of reasons, like the reference's sortReasonsHistogram
        reasons: Dict[str, int] = defaultdict(int)
        for fe in self.nodes.values():
            for r in fe.reasons:
                reasons[r] += 1
        parts = sorted(f"{cnt} {reason}" for reason, cnt in reasons.items())
        return f"0/{len(self.nodes)} nodes are unavailable: {', '.join(parts)}."
