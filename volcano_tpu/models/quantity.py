"""Kubernetes-style quantity parsing.

The framework is standalone (no Kubernetes client), but resource amounts keep
the familiar quantity syntax ("500m", "4Gi", "2") so that job/node specs read
like the reference's YAML. Semantics follow apimachinery's resource.Quantity
as used by the reference's NewResource (reference: pkg/scheduler/api/
resource_info.go:69-88): cpu is accounted in millicores, memory in bytes,
scalar resources in milli-units.
"""

from __future__ import annotations

import re

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {"n": 1e-9, "u": 1e-6, "m": 1e-3, "": 1.0, "k": 1e3, "K": 1e3,
            "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18}

_QUANT_RE = re.compile(r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)\s*$")


def parse_quantity(value) -> float:
    """Parse a quantity string (or number) into a plain float of base units."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QUANT_RE.match(str(value))
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num, suffix = float(m.group(1)), m.group(2)
    if suffix in _BINARY:
        return num * _BINARY[suffix]
    if suffix in _DECIMAL:
        return num * _DECIMAL[suffix]
    raise ValueError(f"invalid quantity suffix: {value!r}")


def milli_value(value) -> float:
    """Quantity -> milli-units (k8s Quantity.MilliValue)."""
    return parse_quantity(value) * 1000.0
