"""Single-process control plane runner.

Runs the whole framework in one process: object store + admission webhooks +
controllers + scheduler + HTTP API endpoint (+ optional simulated kubelets),
the standalone equivalent of deploying the reference's three binaries and
CRDs onto a cluster (installer/volcano-development.yaml).

    python -m volcano_tpu.cmd.cluster --port 8181 --nodes 4 \
        --node-resources cpu=16,memory=32Gi

Then drive it with vcctl:

    python -m volcano_tpu.cli.vcctl job run -N demo -r 4 -m 4
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from ..apiserver.http import StoreHTTPServer
from ..apiserver.store import ObjectStore
from ..cli.util import parse_resource_list
from ..controllers import ControllerManager
from ..framework.registry import load_plugins_dir
from ..models.objects import Queue, ObjectMeta, QueueSpec
from ..scheduler import Scheduler
from ..utils.kubelet import SimulatedKubelet
from ..utils.test_utils import build_node
from ..webhooks import WebhookManager


def build_cluster(port: int = 8181, nodes: int = 0,
                  node_resources: str = "cpu=8,memory=16Gi",
                  scheduler_conf: str = None, schedule_period: float = 1.0,
                  simulate_kubelet: bool = True,
                  enabled_admission: str = None, plugins_dir: str = None,
                  state_file: str = None):
    import os

    from ..apiserver.persistence import load_store
    store = ObjectStore()
    WebhookManager(store, enabled_admission=enabled_admission)
    if state_file and os.path.exists(state_file):
        load_store(state_file, store=store)   # control-plane resume
    if store.get("queues", "default") is None:
        store.create("queues", Queue(metadata=ObjectMeta(name="default"),
                                     spec=QueueSpec(weight=1)),
                     skip_admission=True)
    for i in range(nodes):
        if store.get("nodes", f"node-{i}") is None:
            store.create("nodes", build_node(
                f"node-{i}", parse_resource_list(node_resources)))
    if plugins_dir:
        load_plugins_dir(plugins_dir)
    manager = ControllerManager(store)
    kubelet = SimulatedKubelet(store) if simulate_kubelet else None
    scheduler = Scheduler(store, scheduler_conf_path=scheduler_conf,
                          schedule_period=schedule_period)
    server = StoreHTTPServer(store, port=port)
    return store, manager, kubelet, scheduler, server


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vc-cluster")
    parser.add_argument("--port", type=int, default=8181)
    parser.add_argument("--nodes", type=int, default=0,
                        help="number of simulated nodes to create")
    parser.add_argument("--node-resources", default="cpu=8,memory=16Gi")
    parser.add_argument("--scheduler-conf", default=None,
                        help="scheduler conf YAML path (hot-reloaded)")
    parser.add_argument("--schedule-period", type=float, default=1.0)
    parser.add_argument("--no-kubelet", action="store_true",
                        help="do not simulate pod execution")
    parser.add_argument("--enabled-admission", default=None,
                        help="comma-separated admission paths to enable")
    parser.add_argument("--plugins-dir", default=None,
                        help="directory of custom scheduler plugin .py files")
    parser.add_argument("--listen-address", default=None,
                        help="host:port for the Prometheus /metrics endpoint")
    parser.add_argument("--state-file", default=None,
                        help="snapshot file for control-plane state "
                             "(restored on start, checkpointed periodically)")
    parser.add_argument("--checkpoint-interval", type=float, default=30.0)
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()

    store, manager, kubelet, scheduler, server = build_cluster(
        port=args.port, nodes=args.nodes, node_resources=args.node_resources,
        scheduler_conf=args.scheduler_conf,
        schedule_period=args.schedule_period,
        simulate_kubelet=not args.no_kubelet,
        enabled_admission=args.enabled_admission,
        plugins_dir=args.plugins_dir, state_file=args.state_file)

    checkpointer = None
    if args.state_file:
        from ..apiserver.persistence import StoreCheckpointer
        checkpointer = StoreCheckpointer(store, args.state_file,
                                         interval=args.checkpoint_interval)
        checkpointer.start()

    metrics_server = None
    if args.listen_address:
        from ..metrics.server import MetricsServer
        host, _, port_s = args.listen_address.rpartition(":")
        metrics_server = MetricsServer(host or "127.0.0.1", int(port_s))
        metrics_server.start()

    stop = threading.Event()

    def tick_kubelet():
        import logging
        while not stop.is_set():
            try:
                kubelet.tick()
            except Exception:
                # e.g. a pod deleted by the job controller between the
                # kubelet's get and update; next tick resyncs
                logging.getLogger(__name__).exception("kubelet tick failed")
            stop.wait(0.2)

    manager.start()
    scheduler.start()
    server.start()
    if kubelet is not None:
        threading.Thread(target=tick_kubelet, daemon=True).start()
    print(f"volcano-tpu control plane listening on :{server.port} "
          f"({args.nodes} nodes)")

    def shutdown(*_):
        stop.set()
        scheduler.stop()
        manager.stop()
        server.stop()
        if checkpointer is not None:
            checkpointer.stop()   # final checkpoint
        if metrics_server is not None:
            metrics_server.stop()
        sys.exit(0)

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)
    signal.pause()
    return 0


if __name__ == "__main__":
    sys.exit(main())
