"""vc-apiserver: the standalone API-server process of the multi-process
deployment (docs/deployment.md).

Serves the object store over HTTP — CRUD, the long-poll change journal
(`/watch`), event recording (`/events`), and remote admission-webhook
registration (`/admissionwebhooks`). The other components (vc-scheduler,
vc-controller-manager, vc-webhook-manager, vcctl) connect with `--server`.
The reference's analogue is the Kubernetes API server itself plus volcano's
CRDs (installer/volcano-development.yaml).

    python -m volcano_tpu.cmd.apiserver --port 8181 [--nodes 4 \
        --node-resources cpu=16,memory=32Gi] [--default-queue]
"""

from __future__ import annotations

import argparse
import sys
import threading

from ..apiserver.http import StoreHTTPServer
from ..apiserver.store import ObjectStore
from ..cli.util import parse_resource_list
from ..models.objects import (Node, NodeStatus, ObjectMeta, Queue, QueueSpec)


def main(argv=None) -> int:
    from ..utils.platform import apply_env_platform
    apply_env_platform()
    parser = argparse.ArgumentParser(prog="vc-apiserver")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8181)
    parser.add_argument("--nodes", type=int, default=0,
                        help="pre-create N simulated nodes")
    parser.add_argument("--node-resources", default="cpu=16,memory=32Gi")
    parser.add_argument("--default-queue", action="store_true",
                        help="pre-create the default queue")
    parser.add_argument("--data-dir", default=None,
                        help="durable state under DIR: segmented "
                             "write-ahead log + snapshot.json, replayed "
                             "crash-consistently on startup (the etcd "
                             "durability role; apiserver/wal.py, "
                             "docs/design/durability.md)")
    parser.add_argument("--checkpoint-interval", type=float, default=30.0,
                        help="WAL compaction interval, seconds (snapshot "
                             "anchor + segment purge)")
    parser.add_argument("--wal-flush-interval", type=float, default=0.05,
                        help="WAL group-commit fsync interval, seconds "
                             "(the bounded acked-but-not-durable window)")
    parser.add_argument("--wal-segment-bytes", type=int,
                        default=64 * 1024 * 1024,
                        help="WAL segment rotation size")
    # multi-tenant serving hub (docs/design/serving.md): the sharded
    # watch fan-out behind /watchstream plus per-tenant admission at the
    # write edge. On by default; --serving-shards 0 disables the hub
    # (clients fall back to the long-poll /watch).
    parser.add_argument("--serving-shards", type=int, default=4)
    parser.add_argument("--tenant-write-rate", type=float, default=1000.0,
                        help="per-tenant write tokens per second")
    parser.add_argument("--tenant-write-burst", type=float, default=2000.0)
    parser.add_argument("--max-subscriptions", type=int, default=1024,
                        help="per-tenant concurrent watch-stream cap")
    # federated control plane (docs/design/federation.md): with
    # --replicate-from this process is a FOLLOWER replica — its store is
    # a read-only mirror fed from the leader's /replicate journal stream
    # (snapshot bootstrap on cold start), and its hub serves watch /
    # watchstream traffic at the leader's rvs.
    parser.add_argument("--replicate-from", default=None, metavar="URL",
                        help="leader apiserver URL; makes this replica a "
                             "follower mirror serving reads and watches")
    parser.add_argument("--replica-name", default=None,
                        help="follower replica name (default host:port)")
    # federation PROCESS mode (docs/design/federation.md "process
    # mode"): --peers makes this process a full federation MEMBER — it
    # runs the leader elector against a peer-pushed lease board, follows
    # whichever replica holds the lease, role-gates its write path, and
    # takes over (bumping the fencing token) when the lease lapses.
    parser.add_argument("--peers", default=None,
                        metavar="NAME=URL,NAME=URL",
                        help="all replica endpoints (this one included); "
                             "enables elector-driven federation")
    parser.add_argument("--advertise-url", default=None, metavar="URL",
                        help="base url peers/clients reach this replica "
                             "at (default http://host:port)")
    parser.add_argument("--bootstrap-leader", action="store_true",
                        help="acquire the lease immediately at boot "
                             "(exactly one replica per fresh set)")
    parser.add_argument("--initial-leader", default=None, metavar="NAME",
                        help="lease-board seed: which peer leads at "
                             "boot (followers only)")
    parser.add_argument("--lease-duration", type=float, default=15.0)
    parser.add_argument("--renew-interval", type=float, default=5.0)
    parser.add_argument("--metrics", default=None, metavar="HOST:PORT",
                        help="also serve the Prometheus /metrics + "
                             "/debug endpoints (incl. "
                             "/debug/replication) from this process — "
                             "the same surface the scheduler exposes")
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()

    store = ObjectStore()
    wal = None
    recovered_rv = 0
    if args.data_dir:
        from ..apiserver.wal import WriteAheadLog, recover_store
        _, recovery = recover_store(args.data_dir, store)
        recovered_rv = recovery["final_rv"]
        if recovery["snapshot_objects"] or recovery["entries_replayed"]:
            print(f"recovered rv={recovered_rv} "
                  f"(snapshot {recovery['snapshot_objects']} objects @ "
                  f"rv {recovery['snapshot_rv']}, "
                  f"{recovery['entries_replayed']} WAL entries, "
                  f"{recovery['torn_records_truncated']} torn records "
                  f"truncated) from {args.data_dir}", flush=True)
        wal = WriteAheadLog(args.data_dir,
                            flush_interval=args.wal_flush_interval,
                            segment_max_bytes=args.wal_segment_bytes,
                            compact_interval=args.checkpoint_interval)
        wal.attach(store)
        wal.start()
    def ensure(kind, obj_):
        try:
            store.create(kind, obj_)
        except KeyError:
            pass   # already restored from the snapshot

    if args.default_queue:
        ensure("queues", Queue(metadata=ObjectMeta(name="default"),
                               spec=QueueSpec(weight=1)))
    if args.nodes:
        rl = parse_resource_list(args.node_resources)
        for i in range(args.nodes):
            ensure("nodes", Node(
                metadata=ObjectMeta(name=f"node-{i}"),
                status=NodeStatus(allocatable=dict(rl), capacity=dict(rl))))
    hub = admission = None
    if args.serving_shards > 0:
        from .. import serving
        from ..serving.admission import AdmissionController
        from ..serving.hub import ServingHub
        admission = AdmissionController(
            write_rate=args.tenant_write_rate,
            write_burst=args.tenant_write_burst,
            max_subscriptions=args.max_subscriptions)
        hub = ServingHub(store, shards=args.serving_shards,
                         admission=admission)
        serving.set_active(hub=hub, admission=admission)
    follower = None
    member = None
    if args.peers:
        from ..replication import set_active
        from ..replication.election import FederationMember
        peers = {}
        for part in args.peers.split(","):
            pname, _, purl = part.partition("=")
            if not pname or not purl:
                parser.error(f"malformed --peers entry {part!r} "
                             "(want NAME=URL)")
            peers[pname.strip()] = purl.strip()
        name = args.replica_name or f"{args.host}:{args.port}"
        advertise = args.advertise_url or f"http://{args.host}:{args.port}"
        initial = args.initial_leader or ""
        member = FederationMember(
            name, store, hub=hub, peers=peers, advertise_url=advertise,
            lease_duration=args.lease_duration,
            renew_interval=args.renew_interval,
            bootstrap_leader=args.bootstrap_leader,
            initial_leader=initial,
            initial_leader_url=peers.get(initial, ""),
            local_recovery_floor=(recovery["fence_floor"]
                                  if recovered_rv > 0 else None))
        set_active(member=member)
    elif args.replicate_from:
        from ..replication import set_active
        from ..replication.follower import (FollowerReplica,
                                            HTTPReplicationSource)
        source = HTTPReplicationSource(args.replicate_from)
        name = args.replica_name or f"{args.host}:{args.port}"
        follower = FollowerReplica(name, source, store=store, hub=hub)
        resume_local = False
        if recovered_rv > 0:
            # federation restart fast path (docs/design/durability.md):
            # local WAL recovery already re-anchored the mirror at the
            # leader's rvs — resume the journal pull from there and only
            # fall back to the peer snapshot bootstrap when the sync
            # loop proves the log behind the leader's retained window
            # (gap -> catch-up relist -> bootstrap, follower.py).
            # Guarded like FederationMember._ensure_following
            # (election.py): the local log is only trusted while the
            # upstream's fence epoch is <= the recovered floor (no
            # takeover since the log's last durable fence record) and
            # our rv does not run AHEAD of the upstream head — a
            # rebuilt/diverged upstream whose rv space overlaps ours
            # contiguously would otherwise resume silently divergent
            # (the sync loop sees no gap to trip on).
            try:
                up_head = source.current_rv()
                _, _, gone, up_epoch = source.collect(up_head,
                                                      timeout=0.0)
                resume_local = (not gone
                                and up_epoch <= recovery["fence_floor"]
                                and recovered_rv <= up_head)
            except Exception as e:
                print(f"follower: upstream probe failed ({e}); "
                      f"falling back to snapshot bootstrap", flush=True)
        if resume_local:
            print(f"follower resuming from local WAL at rv "
                  f"{recovered_rv} (peer bootstrap skipped)", flush=True)
        else:
            follower.bootstrap()              # cold-start snapshot
        follower.start()                      # continuous journal pull
        set_active(follower=follower)
    metrics_server = None
    if args.metrics:
        from ..metrics.server import MetricsServer
        mhost, _, mport = args.metrics.rpartition(":")
        metrics_server = MetricsServer(mhost or "127.0.0.1", int(mport))
        metrics_server.start()
    server = StoreHTTPServer(store, host=args.host, port=args.port,
                             hub=hub, admission=admission, member=member)
    server.start()
    if member is not None:
        if args.bootstrap_leader:
            member.step()   # claim the lease before the first client
        member.start()
        role = f"member:{member.role()}"
    elif follower is not None:
        role = f"follower of {args.replicate_from}"
    else:
        role = "leader"
    print(f"vc-apiserver ({role}) serving on {args.host}:{server.port}",
          flush=True)
    stop = threading.Event()
    import signal as _signal

    def _graceful(signum, frame):
        stop.set()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(sig, _graceful)
    stop.wait()
    if member is not None:
        member.stop()
    if follower is not None:
        follower.stop()
    if metrics_server is not None:
        metrics_server.stop()
    if wal is not None:
        # stop accepting writes BEFORE the final flush+compact: an acked
        # write landing after the last fsync would be lost on restart
        server.stop()
        wal.close(final_compact=True)   # durable shutdown
    return 0


if __name__ == "__main__":
    sys.exit(main())
