"""vc-controller-manager binary equivalent
(reference: cmd/controller-manager/app/server.go): runs all registered
controllers with optional leader election.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

from ..apiserver.store import ObjectStore
from ..controllers import ControllerManager, JobController
from ..utils.leaderelection import LeaderElector


def add_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--server", default=None,
                        help="remote apiserver URL (multi-process mode)")
    parser.add_argument("--worker-num", type=int, default=4,
                        help="job controller worker shard count")
    parser.add_argument("--max-requeue-num", type=int, default=15)
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--version", action="store_true")


def run_controllers(store: ObjectStore, args) -> ControllerManager:
    from ..controllers import (GarbageCollector, PodGroupController,
                               QueueController)
    controllers = [
        JobController(workers=args.worker_num,
                      max_requeue_num=args.max_requeue_num),
        QueueController(), PodGroupController(), GarbageCollector(),
    ]
    manager = ControllerManager(store, controllers)
    if args.leader_elect:
        identity = f"{os.uname().nodename}-{os.getpid()}"
        LeaderElector(store, identity, lease_name="vc-controller-manager",
                      on_started_leading=manager.start,
                      on_stopped_leading=manager.stop).start()
    else:
        manager.start()
    return manager


def main(argv=None) -> int:
    from ..utils.platform import apply_env_platform
    apply_env_platform()
    parser = argparse.ArgumentParser(prog="vc-controller-manager")
    add_flags(parser)
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()
    if args.server:
        from ..apiserver.remote import RemoteStore
        store = RemoteStore(args.server)
        store.run()
    else:
        store = ObjectStore()
    run_controllers(store, args)
    print("vc-controller-manager running against "
          + (args.server or "embedded store"), flush=True)
    threading.Event().wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
