"""vc-scheduler binary equivalent (reference: cmd/scheduler/app/server.go).

Runs the scheduler component alone against an embedded store with leader
election and a Prometheus endpoint. For a full control plane in one
process use cmd.cluster; this entry point exists for component-parity and
HA topologies where several scheduler candidates share one store.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

from ..apiserver.store import ObjectStore
from ..scheduler import Scheduler
from ..utils.leaderelection import LeaderElector


def add_flags(parser: argparse.ArgumentParser) -> None:
    """cmd/scheduler/app/options/options.go:81-108"""
    parser.add_argument("--server", default=None,
                        help="remote apiserver URL (multi-process mode, "
                             "docs/deployment.md); default: embedded store")
    parser.add_argument("--scheduler-name", default="volcano")
    parser.add_argument("--scheduler-conf", default=None)
    parser.add_argument("--schedule-period", type=float, default=1.0)
    parser.add_argument("--default-queue", default="default")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--lock-object-namespace", default="volcano-system")
    parser.add_argument("--listen-address", default=":8080")
    parser.add_argument("--plugins-dir", default=None)
    parser.add_argument("--percentage-nodes-to-find", type=int, default=0,
                        help="accepted for flag parity; the TPU solver "
                             "evaluates all nodes exhaustively")
    parser.add_argument("--enable-tracing", action="store_true",
                        help="turn on the cycle flight recorder + pod "
                             "lifecycle ledger + metrics timeseries "
                             "(/debug/trace, /debug/cycles, /debug/pending, "
                             "/debug/latency, /debug/timeseries on "
                             "--listen-address; <2%% cycle overhead); "
                             "also enabled by VOLCANO_TRACE=1")
    parser.add_argument("--trace-cycles", type=int, default=None,
                        help="flight-recorder ring buffer: how many recent "
                             "cycles to keep (default 64, or "
                             "VOLCANO_TRACE_CAPACITY when set)")
    parser.add_argument("--version", action="store_true")


def run_scheduler(store: ObjectStore, args) -> Scheduler:
    if args.plugins_dir:
        from ..framework.registry import load_plugins_dir
        load_plugins_dir(args.plugins_dir)
    scheduler = Scheduler(store, scheduler_name=args.scheduler_name,
                          scheduler_conf_path=args.scheduler_conf,
                          schedule_period=args.schedule_period)
    if args.leader_elect:
        identity = f"{os.uname().nodename}-{os.getpid()}"
        elector = LeaderElector(
            store, identity, lease_name="vc-scheduler",
            on_started_leading=scheduler.start,
            on_stopped_leading=scheduler.stop)
        # lease fencing (docs/design/failover.md): run_once no-ops while
        # standby, and bind/patch writes carry the elector's token so a
        # deposed incarnation can't commit after a takeover
        scheduler.elector = elector
        scheduler.cache.fence_source = lambda: elector.fencing_token
        elector.start()
    else:
        scheduler.start()
    return scheduler


def main(argv=None) -> int:
    from ..utils.platform import apply_env_platform
    apply_env_platform()
    parser = argparse.ArgumentParser(prog="vc-scheduler")
    add_flags(parser)
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()
    from ..trace import tracer
    if args.enable_tracing:
        # an explicit --trace-cycles wins; else VOLCANO_TRACE_CAPACITY;
        # else the tracer's default (64)
        cap = args.trace_cycles
        if cap is None:
            cap = tracer.env_capacity()
        tracer.enable(capacity=cap)
    elif tracer.enable_from_env() and args.trace_cycles is not None:
        tracer.configure(args.trace_cycles)
    if args.server:
        from ..apiserver.remote import RemoteStore
        store = RemoteStore(args.server)
        store.run()
    else:
        store = ObjectStore()
    run_scheduler(store, args)
    from ..metrics.server import MetricsServer
    host, _, port_s = args.listen_address.rpartition(":")
    try:
        MetricsServer(host or "127.0.0.1", int(port_s)).start()
    except OSError as e:
        # a second candidate on the same host must not die over the
        # metrics port (the reference runs candidates in separate pods);
        # leader election and scheduling proceed without exposition
        print(f"metrics endpoint unavailable ({e}); continuing without",
              file=sys.stderr)
    print("vc-scheduler running against "
          + (args.server or "embedded store"), flush=True)
    threading.Event().wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
