"""vc-deploy: one-command control-plane deployment.

The standalone analogue of the reference's one-file installer
(installer/volcano-development.yaml: three Deployments + admission
registration against the API server): brings up the four-process control
plane — apiserver, webhook-manager (TLS admission, CA-bundle registered),
controller-manager, scheduler — waits for admission to be live, runs a
smoke job through the full path (webhook validate -> job controller ->
podgroup -> gang schedule -> binds), reports, and tears everything down
(``--keep`` leaves it running for interactive use).

    python -m volcano_tpu.cmd.deploy            # up + smoke + teardown
    make deploy                                 # same
"""

from __future__ import annotations

import argparse
import signal
import socket
import subprocess
import sys
import time


def add_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--port", type=int, default=0,
                        help="apiserver port (0 = pick a free one)")
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--node-resources", default="cpu=16,memory=32Gi")
    parser.add_argument("--smoke-replicas", type=int, default=4)
    parser.add_argument("--timeout", type=float, default=120.0)
    parser.add_argument("--keep", action="store_true",
                        help="leave the control plane running (Ctrl-C "
                             "tears it down)")
    parser.add_argument("--scheduler-conf", default=None)
    parser.add_argument("--version", action="store_true")


def _spawn(module: str, *args: str) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, "-m", module, *args])


def log(msg: str) -> None:
    print(f"[deploy] {msg}", flush=True)


def main(argv=None) -> int:
    from ..utils.platform import apply_env_platform
    apply_env_platform()
    parser = argparse.ArgumentParser(prog="vc-deploy")
    add_flags(parser)
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()

    from ..apiserver.http import ApiError, StoreClient
    from ..models.objects import (Container, Job, JobSpec, ObjectMeta,
                                  PodSpec, PodTemplate, TaskSpec)

    port = args.port
    if port == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    url = f"http://127.0.0.1:{port}"
    procs: list = []
    ok = False

    def teardown() -> None:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    def make_job(name: str, replicas: int, min_available: int) -> Job:
        return Job(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=JobSpec(
                min_available=min_available, queue="default",
                tasks=[TaskSpec(
                    name="main", replicas=replicas,
                    template=PodTemplate(
                        metadata=ObjectMeta(name="main"),
                        spec=PodSpec(containers=[Container(
                            name="main",
                            requests={"cpu": "1", "memory": "1Gi"})])))]))

    try:
        log(f"apiserver on {url} with {args.nodes} synthetic nodes")
        procs.append(_spawn("volcano_tpu.cmd.apiserver",
                            "--port", str(port), "--default-queue",
                            "--nodes", str(args.nodes),
                            "--node-resources", args.node_resources))
        client = StoreClient(url)
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            try:
                client.list("queues")
                break
            except Exception:
                time.sleep(0.3)
        else:
            log("apiserver did not come up")
            return 1

        log("webhook-manager (TLS admission, CA bundle registered)")
        procs.append(_spawn("volcano_tpu.cmd.webhook_manager",
                            "--server", url, "--port", "0"))
        log("controller-manager")
        procs.append(_spawn("volcano_tpu.cmd.controller_manager",
                            "--server", url))
        log("scheduler")
        sched = ["volcano_tpu.cmd.scheduler", "--server", url,
                 "--schedule-period", "0.5"]
        if args.scheduler_conf:
            sched += ["--scheduler-conf", args.scheduler_conf]
        procs.append(_spawn(*sched))

        # admission live = an invalid job is rejected over the TLS callback
        log("waiting for admission registration (invalid job must be "
            "rejected)")
        rejected = False
        while time.monotonic() < deadline and not rejected:
            try:
                client.create("jobs", make_job("deploy-bad", 2, 5))
                client.delete("jobs", "deploy-bad", "default")
            except ApiError as e:
                if e.code == 422:
                    rejected = True
                    break
            time.sleep(0.4)   # outside the try: non-422 errors (webhook
            #                   still booting) must not busy-spin
        if not rejected:
            log("FAIL: admission never became live")
            return 1
        log("admission live (422 on invalid job)")

        # smoke job through the whole control plane
        n = args.smoke_replicas
        log(f"smoke job: gang of {n}")
        client.create("jobs", make_job("deploy-smoke", n, n))
        bound: dict = {}
        while time.monotonic() < deadline:
            pods = [p for p in client.list("pods", "default")
                    if p.metadata.name.startswith("deploy-smoke-")]
            bound = {p.metadata.name: p.spec.node_name
                     for p in pods if p.spec.node_name}
            if len(bound) >= n:
                break
            time.sleep(0.4)
        if len(bound) < n:
            log(f"FAIL: only {len(bound)}/{n} smoke pods bound")
            return 1
        pg = next((g for g in client.list("podgroups", "default")
                   if g.metadata.name.startswith("deploy-smoke")), None)
        log(f"smoke job bound: {len(bound)}/{n} pods on "
            f"{len(set(bound.values()))} nodes; podgroup phase "
            f"{pg.status.phase if pg else '?'}")
        ok = True
        if args.keep:
            log(f"control plane left running on {url} (Ctrl-C to stop); "
                "submit work with:")
            log(f"  python -m volcano_tpu.cli.vcctl --server {url} "
                "job run -N demo -r 4 -m 4")
            try:
                while all(p.poll() is None for p in procs):
                    time.sleep(1.0)
            except KeyboardInterrupt:
                return 0
            log("FAIL: a control-plane component exited; tearing down")
            return 1
        return 0
    finally:
        if not args.keep or not ok:
            log("tearing down")
            teardown()
            log("deployment verified and torn down" if ok else "failed")
        else:
            teardown()


if __name__ == "__main__":
    sys.exit(main())
