"""vc-webhook-manager binary equivalent
(reference: cmd/webhook-manager/app/server.go): registers the enabled
admission services on a store and exposes it over HTTP.
"""

from __future__ import annotations

import argparse
import sys
import threading

from ..apiserver.http import StoreHTTPServer
from ..apiserver.store import ObjectStore
from ..webhooks import WebhookManager


def add_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--enabled-admission", default=None,
                        help="comma-separated admission service paths")
    parser.add_argument("--port", type=int, default=8443)
    parser.add_argument("--server", default=None,
                        help="remote apiserver URL: serve the admission "
                             "endpoint and self-register the webhooks "
                             "(multi-process mode, docs/deployment.md)")
    parser.add_argument("--tls-cert-dir", default=None,
                        help="directory for the self-signed CA + serving "
                             "cert (generated on first start; default: a "
                             "per-process temp dir). The CA is registered "
                             "as the webhooks' trust bundle.")
    parser.add_argument("--insecure-http", action="store_true",
                        help="serve the admission endpoint over plain "
                             "HTTP (TLS is on by default in --server "
                             "mode, matching the reference)")
    parser.add_argument("--version", action="store_true")


def main(argv=None) -> int:
    from ..utils.platform import apply_env_platform
    apply_env_platform()
    parser = argparse.ArgumentParser(prog="vc-webhook-manager")
    add_flags(parser)
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()
    if args.server:
        # multi-process mode: serve the admission endpoint; the apiserver
        # calls back per matching operation after self-registration
        from ..apiserver.remote import RemoteStore
        from ..webhooks.router import AdmissionHTTPServer
        lookups = RemoteStore(args.server)
        lookups.run()
        tls_dir = None
        if not args.insecure_http:
            tls_dir = args.tls_cert_dir
            if tls_dir is None:
                import atexit
                import shutil
                import tempfile
                tls_dir = tempfile.mkdtemp(prefix="vc-webhook-certs-")
                # ephemeral keys: regenerated + re-registered every start,
                # so nothing needs them after exit
                atexit.register(shutil.rmtree, tls_dir, ignore_errors=True)
        endpoint = AdmissionHTTPServer(
            lookups, enabled_admission=args.enabled_admission,
            port=args.port, tls_cert_dir=tls_dir)
        endpoint.start()
        endpoint.register_with(args.server)
        print(f"vc-webhook-manager serving {len(endpoint.services)} "
              f"admission services on {endpoint.scheme}://127.0.0.1:"
              f"{endpoint.port}, registered with {args.server}", flush=True)
        threading.Event().wait()
        return 0
    store = ObjectStore()
    manager = WebhookManager(store, enabled_admission=args.enabled_admission)
    server = StoreHTTPServer(store, port=args.port)
    server.start()
    print(f"vc-webhook-manager serving {len(manager.services)} admission "
          f"services on :{server.port}", flush=True)
    threading.Event().wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
