"""vc-webhook-manager binary equivalent
(reference: cmd/webhook-manager/app/server.go): registers the enabled
admission services on a store and exposes it over HTTP.
"""

from __future__ import annotations

import argparse
import sys
import threading

from ..apiserver.http import StoreHTTPServer
from ..apiserver.store import ObjectStore
from ..webhooks import WebhookManager


def add_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--enabled-admission", default=None,
                        help="comma-separated admission service paths")
    parser.add_argument("--port", type=int, default=8443)
    parser.add_argument("--version", action="store_true")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vc-webhook-manager")
    add_flags(parser)
    args = parser.parse_args(argv)
    if args.version:
        from ..version import print_version_and_exit
        print_version_and_exit()
    store = ObjectStore()
    manager = WebhookManager(store, enabled_admission=args.enabled_admission)
    server = StoreHTTPServer(store, port=args.port)
    server.start()
    print(f"vc-webhook-manager serving {len(manager.services)} admission "
          f"services on :{server.port}")
    threading.Event().wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
