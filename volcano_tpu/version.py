"""Version info (reference: pkg/version/version.go)."""

from __future__ import annotations

import platform
import sys

VERSION = "0.1.0"
API_VERSION = "v1alpha1"


def version_string() -> str:
    return (f"volcano-tpu version: {VERSION}\n"
            f"API version: {API_VERSION}\n"
            f"Python version: {sys.version.split()[0]}\n"
            f"Platform: {platform.system().lower()}/{platform.machine()}")


def print_version_and_exit() -> None:
    print(version_string())
    raise SystemExit(0)
