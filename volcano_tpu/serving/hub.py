"""Sharded watch hub: fan-out serving over the store journal
(docs/design/serving.md).

The store's synchronous watch bus is built for the handful of in-process
informers (cache, controllers); a serving edge with thousands of remote
watchers needs a different shape. The hub subscribes NOTHING on the
store — it is a pure journal consumer:

* **Shards** — subscribers hash by client id onto N dispatch shards
  (crc32, so placement is a pure function of the id and double runs are
  identical). Each shard reads the journal once per round from the
  minimum cursor of its subscribers and fans the burst out; one shard's
  slow consumer never blocks another shard's dispatch.
* **Cursors** — every subscriber carries a persistent journal cursor
  (the rv-sorted, gap-free journal from the bind pipeline is the
  stream). A cursor that falls off the journal window gets a structured
  ``relist`` frame — the client re-lists and re-anchors, exactly the
  RemoteStore resync path — instead of silently missing events.
* **Coalescing** — everything a dispatch round finds for one subscriber
  lands in ONE frame: a 50k-bind flush reaches an interested client as
  a handful of framed batches (one per published journal extent seen),
  not 50k deliveries. ``volcano_serving_batches_total`` vs
  ``volcano_serving_events_total`` is the measured ratio.
* **Server-side filters** — per-subscriber kind sets and field filters
  evaluated in the hub, ONCE per distinct filter per burst (the native
  ``attr_eq_filter_pairs`` entry classifies a whole burst in one call
  when the filter is a declared attribute equality; Python fallback
  otherwise). Filter FLIPS keep the PR-3 lifecycle semantics: pass→fail
  delivers DELETED, fail→pass delivers ADDED, only pass→pass is
  MODIFIED.

Frames are plain dicts carrying journal object REFS (the store replaces
objects wholesale, never mutates — the same property the journal
relies on); the HTTP layer encodes them at the wire. Frame chain
integrity: each frame carries ``prev`` (the previous frame's ``to_rv``)
so a client can detect a lost frame and ``rewind`` — the storm gate's
fault-recovery contract.

Two drive modes: ``start()`` runs one dispatch thread per shard (the
serving process), ``pump()`` dispatches synchronously (the simulator's
deterministic tick hook and tests).
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

from ..apiserver.store import ObjectStore
from .admission import AdmissionController

# native attribute-equality classification (fastmodel.attr_eq_filter_pairs,
# the PR-8 entry): resolved lazily, shared probe state
_NATIVE = [None, False]


def _native():
    if not _NATIVE[1]:
        _NATIVE[1] = True
        try:
            from ..native.build import fastmodel
            fm = fastmodel()
            if fm is not None and hasattr(fm, "attr_eq_filter_pairs"):
                _NATIVE[0] = fm
        except Exception:
            _NATIVE[0] = None
    return _NATIVE[0]


class Subscription:
    """One client's session on the hub. Owned by exactly one shard."""

    MAX_OUTBOX = 256   # frames; overflow resets the subscriber via relist
    #                    (a consumer that stopped draining re-lists rather
    #                    than pinning unbounded memory server-side)

    def __init__(self, client_id: str, tenant: str, kinds, filter_attr,
                 filter_fn, cursor: int):
        self.hub = None   # backref set at subscribe (relist accounting)
        self.client_id = client_id
        self.tenant = tenant
        self.kinds = frozenset(kinds) if kinds else None
        # ((a0, a1), expected) — declared attribute equality, the native
        # classification path; filter_fn is the authority when both given
        self.filter_attr = filter_attr
        self.filter_fn = filter_fn
        self.cursor = int(cursor)       # last journal rv this sub covered
        self.last_framed = int(cursor)  # to_rv of the last frame enqueued
        # the rv this session was anchored at, frozen at subscribe time.
        # The streaming handler's hello frame MUST advertise this, not
        # the live cursor: shard dispatch can enqueue frames and advance
        # ``cursor`` before the handler writes its hello, and a hello
        # ahead of the queued frames makes the client count every one of
        # them as a duplicate (or skip them as already-applied).
        self.anchor = int(cursor)
        self.outbox: deque = deque()
        self.cond = threading.Condition()
        # keys currently PASSING the filter from this subscriber's view —
        # the old_p half of the flip classification (the journal has no
        # old object). Primed from the store at subscribe time.
        self._passing: set = set()
        self.frames_sent = 0
        self.events_sent = 0
        self.relists = 0
        self.closed = False

    @property
    def filtered(self) -> bool:
        return self.filter_attr is not None or self.filter_fn is not None

    def filter_key(self):
        if self.filter_attr is not None:
            (a0, a1), exp = self.filter_attr
            return ("attr", a0, a1, exp)
        if self.filter_fn is not None:
            return ("fn", id(self.filter_fn))
        return None

    def _passes(self, o) -> bool:
        if self.filter_fn is not None:
            return bool(self.filter_fn(o))
        (a0, a1), exp = self.filter_attr
        return getattr(getattr(o, a0, None), a1, None) == exp

    # -- consumer side -----------------------------------------------------

    def take_frames(self) -> List[dict]:
        """Drain everything queued (non-blocking; the pump-mode client)."""
        with self.cond:
            frames = list(self.outbox)
            self.outbox.clear()
        return frames

    def next_frame(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Block for the next frame (the streaming HTTP handler)."""
        with self.cond:
            if not self.outbox:
                self.cond.wait(timeout)
            return self.outbox.popleft() if self.outbox else None

    # -- shard side (shard lock held) --------------------------------------

    def _enqueue(self, frame: dict) -> None:
        overflowed = False
        with self.cond:
            if len(self.outbox) >= self.MAX_OUTBOX:
                # slow consumer: reset via relist instead of growing
                frame = {"relist": True, "rv": frame.get("to_rv",
                                                         frame.get("rv", 0)),
                         "prev": self.last_framed}
                if self.hub is not None:
                    frame["epoch"] = self.hub.epoch
                self.outbox.clear()
                self.relists += 1
                overflowed = True
            self.outbox.append(frame)
            self.cond.notify_all()
        if overflowed and self.hub is not None:
            self.hub._note_relist()


class HubShard:
    """One dispatch shard: a set of subscribers + the journal read loop."""

    def __init__(self, index: int, store: ObjectStore, hub: "ServingHub"):
        self.index = index
        self.store = store
        self.hub = hub
        self.lock = threading.Lock()
        self.subs: List[Subscription] = []
        self._thread: Optional[threading.Thread] = None

    # -- membership --------------------------------------------------------

    def add(self, sub: Subscription) -> None:
        with self.lock:
            self.subs.append(sub)

    def remove(self, sub: Subscription) -> None:
        with self.lock:
            if sub in self.subs:
                self.subs.remove(sub)
        sub.closed = True
        with sub.cond:
            sub.cond.notify_all()

    def depth(self) -> int:
        with self.lock:
            return sum(len(s.outbox) for s in self.subs)

    def pressure(self) -> tuple:
        """(total queued frames, worst outbox fill fraction) — the
        backpressure surface: a fill fraction approaching 1.0 means a
        subscriber is about to take the overflow-relist reset."""
        with self.lock:
            depths = [len(s.outbox) for s in self.subs]
        total = sum(depths)
        worst = max(depths, default=0) / float(Subscription.MAX_OUTBOX)
        return total, worst

    # -- dispatch ----------------------------------------------------------

    def dispatch_once(self, timeout: float = 0.0) -> int:
        """One fan-out round: read the journal once from the shard's
        minimum cursor, deliver ONE coalesced frame per subscriber with
        news, relist cursors that fell off the window. Returns frames
        enqueued."""
        with self.lock:
            subs = list(self.subs)
        if not subs:
            if timeout:
                self.hub._stop.wait(timeout)
            return 0
        frames = 0
        head, tail = self.store.journal_window()
        # structured relist for cursors that fell off the journal window
        # (the window rolled past them, or a snapshot restore cleared it)
        for sub in subs:
            if sub.cursor + 1 < head:
                self._relist(sub, tail)
                frames += 1
        min_cursor = min(sub.cursor for sub in subs)
        burst, tail, resync = self.hub._shared_burst(min_cursor, head,
                                                     timeout)
        if resync:
            # the window moved between our check and the read (or the
            # journal was force-cleared): re-anchor every lagging cursor
            head, tail = self.store.journal_window()
            for sub in subs:
                if sub.cursor < tail and sub.cursor + 1 < head:
                    self._relist(sub, tail)
                    frames += 1
            return frames
        if burst is None:
            return frames
        events = burst.events
        epoch = self.hub.epoch
        encoder = self.hub.encoder
        enc = burst.encoded(encoder) if encoder is not None else None
        from bisect import bisect_right
        for sub in subs:
            if sub.cursor >= tail:
                continue
            # per-frame latency is attributed per SUBSCRIBER (the clock
            # starts when this subscriber's selection starts, not when
            # the round started) — the shared burst index means the
            # first consumer pays the build and everyone else measures
            # only their own slice
            t0 = time.perf_counter()
            start = bisect_right(burst.rvs, sub.cursor)
            delivered, idxs = self._select(sub, burst, start)
            considered = len(events) - start
            sub.cursor = tail
            if not delivered:
                continue   # cursor advanced silently: nothing of interest
            frame = {"prev": sub.last_framed,
                     "from_rv": events[start][0], "to_rv": tail,
                     "events": delivered, "coalesced_from": considered,
                     "epoch": epoch}
            if enc is not None:
                # shared per-event object bytes: encoded ONCE per burst,
                # every subscriber's frame carries refs into the same
                # list (the wire wrapper re-labels per-sub actions)
                frame["encoded"] = [enc[i] for i in idxs]
            sub.last_framed = tail
            sub._enqueue(frame)
            sub.frames_sent += 1
            sub.events_sent += len(delivered)
            frames += 1
            self.hub._note_frame(len(delivered),
                                 (time.perf_counter() - t0) * 1000.0)
        self.hub._note_depth(self.index, *self.pressure())
        return frames

    def _relist(self, sub: Subscription, tail: int) -> None:
        """Push the structured relist signal and re-anchor the cursor:
        the client must re-list and resume from ``rv`` (exactly the
        informer resync-after-watch-expiry contract)."""
        sub._enqueue({"relist": True, "rv": tail, "prev": sub.last_framed,
                      "epoch": self.hub.epoch})
        sub.cursor = tail
        sub.last_framed = tail
        sub._passing.clear()
        sub.relists += 1
        self.hub._note_relist()

    def _select(self, sub: Subscription, burst: "_BurstIndex",
                start: int):
        """Apply the subscriber's kind + field filters to the burst's
        ``[start:]`` slice, classifying flips as lifecycle transitions
        (see module doc). Per-sub cost is proportional to DELIVERED
        events, not burst size: the burst index precomputes, once per
        distinct filter per round, the verdict vector, the passing
        indices and a failing-key map — so 1k identically-filtered
        subscribers pay one classification plus their own slices.

        Returns ``(delivered, idxs)`` — the delivered event tuples plus
        their burst indices, so the caller can attach shared per-event
        encoded bytes without re-deriving positions."""
        from bisect import bisect_left
        events = burst.events
        kinds = sub.kinds
        if not sub.filtered:
            if kinds is None:
                # firehose: the tail slice is cached per start index and
                # SHARED across every unfiltered subscriber at the same
                # cursor (frames carry refs, never mutate)
                return burst.tail_slice(start), range(start, len(events))
            out = []
            for kind in kinds:
                idx = burst.kind_idx().get(kind)
                if idx:
                    out.extend(idx[bisect_left(idx, start):])
            if len(kinds) > 1:
                out.sort()
            return [events[i] for i in out], out
        pass_set, pass_idx = burst.filter_index(sub)
        keys = burst.keys()
        key_idx = burst.key_idx()
        passing = sub._passing
        # candidate indices: every passing event past the cursor, plus
        # FAILING events whose key this subscriber currently sees as
        # passing (the potential pass->fail flips) — including keys that
        # BECOME passing within this very burst (add-then-flip). Cost is
        # O(delivered + |passing|), never O(burst).
        cand = pass_idx[bisect_left(pass_idx, start):]
        flip_keys = set(passing)
        flip_keys.update(keys[i] for i in cand)
        fail_idx = []
        for key in flip_keys:
            for i in key_idx.get(key, ()):
                if i >= start and i not in pass_set:
                    fail_idx.append(i)
        if fail_idx:
            cand = sorted(set(cand).union(fail_idx))
        out = []
        idxs = []
        for i in cand:
            rv, action, kind, o = events[i]
            if kinds is not None and kind not in kinds:
                continue
            key = keys[i]
            old_p = key in passing
            if action == "DELETED":
                if old_p:
                    passing.discard(key)
                    out.append((rv, "DELETED", kind, o))
                    idxs.append(i)
                continue
            if i in pass_set:
                passing.add(key)
                # fail->pass (or a fresh ADDED) surfaces as ADDED; only
                # pass->pass is MODIFIED — the four delivery paths of
                # the store's filtered watches, evaluated hub-side
                out.append((rv, "MODIFIED" if old_p else "ADDED", kind, o))
                idxs.append(i)
            elif old_p:
                passing.discard(key)
                out.append((rv, "DELETED", kind, o))
                idxs.append(i)
        return out, idxs

    # -- threaded mode -----------------------------------------------------

    def run_loop(self) -> None:
        while not self.hub._stop.is_set():
            try:
                self.dispatch_once(timeout=self.hub.poll_timeout)
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "hub shard %d dispatch failed", self.index)
                self.hub._stop.wait(0.2)


class _BurstIndex:
    """Shared indexes over one fetched burst: rvs for cursor bisects,
    (kind, key) per event, per-kind and per-key index lists, the (o, o)
    pair list the native classifier consumes, per DISTINCT filter the
    passing index set, cached firehose tail slices, and (when the hub
    has an encoder) the per-event encoded object bytes. Everything here
    is computed at most once per BURST no matter how many subscribers —
    or how many SHARDS (the hub keeps a small cross-shard cache, see
    ``ServingHub._shared_burst``) — consume it: the server-side cost of
    1k identically-filtered watchers is ONE classification and ONE
    serialization pass.

    Lazy memoization is guarded by an RLock because shard dispatch
    threads share one index; builders are idempotent so the lock only
    prevents duplicated work and torn ``_pairs``/``_id2idx`` pairs."""

    def __init__(self, store, events: list):
        self.store = store
        self.events = events
        self.rvs = [e[0] for e in events]
        self._lock = threading.RLock()
        self._keys: Optional[list] = None
        self._kind_idx: Optional[dict] = None
        self._key_idx: Optional[dict] = None
        self._pairs: Optional[list] = None
        self._id2idx: Optional[dict] = None
        self._filters: dict = {}
        self._slices: dict = {}
        self._encoded: Optional[list] = None
        self._encoder = None

    def keys(self) -> list:
        with self._lock:
            if self._keys is None:
                key_of = self.store.key_of
                self._keys = [(e[2], key_of(e[2], e[3]))
                              for e in self.events]
            return self._keys

    def kind_idx(self) -> dict:
        with self._lock:
            if self._kind_idx is None:
                idx: dict = {}
                for i, e in enumerate(self.events):
                    idx.setdefault(e[2], []).append(i)
                self._kind_idx = idx
            return self._kind_idx

    def key_idx(self) -> dict:
        """(kind, key) -> [indices] over the whole burst (shared by
        every filtered subscriber's flip lookup)."""
        with self._lock:
            if self._key_idx is None:
                idx: dict = {}
                for i, key in enumerate(self.keys()):
                    idx.setdefault(key, []).append(i)
                self._key_idx = idx
            return self._key_idx

    def tail_slice(self, start: int) -> list:
        """``events[start:]``, cached per start index: N firehose
        subscribers at the same cursor share ONE slice instead of each
        copying the burst."""
        with self._lock:
            got = self._slices.get(start)
            if got is None:
                got = self._slices[start] = self.events[start:]
            return got

    def encoded(self, encoder) -> list:
        """Per-event encoded object bytes, serialized ONCE per burst.
        ``encoder(kind, obj) -> bytes`` is the hub's wire codec; the
        per-subscriber frame wrapper carries rv/action/kind, so the
        heavy object payload is byte-shared even when a filtered
        subscriber re-labels the action."""
        with self._lock:
            if self._encoded is None or self._encoder is not encoder:
                self._encoded = [encoder(e[2], e[3]) for e in self.events]
                self._encoder = encoder
            return self._encoded

    def _pair_list(self) -> list:
        if self._pairs is None:
            self._pairs = [(e[3], e[3]) for e in self.events]
            # the index key is the PAIR TUPLE's identity, not the
            # object's: a DELETED journal entry reuses the ADDED/
            # MODIFIED entry's object instance, but each pair tuple
            # here is freshly allocated and unique per index
            self._id2idx = {id(p): i
                            for i, p in enumerate(self._pairs)}
        return self._pairs

    def filter_index(self, sub: Subscription) -> tuple:
        """(pass_set, pass_idx) for the subscriber's filter, computed
        once per distinct filter per burst — natively via the PR-8
        ``attr_eq_filter_pairs`` entry for declared attribute equalities
        ((o, o) pairs: pass->pass membership IS the verdict, one C call
        per burst per filter), Python ``filter_fn`` otherwise."""
        with self._lock:
            fkey = sub.filter_key()
            got = self._filters.get(fkey)
            if got is not None:
                return got
            events = self.events
            pass_idx = None
            if sub.filter_attr is not None and sub.filter_fn is None:
                fm = _native()
                if fm is not None:
                    (a0, a1), exp = sub.filter_attr
                    pairs = self._pair_list()
                    try:
                        delivery, _ = fm.attr_eq_filter_pairs(pairs, a0,
                                                              a1, exp)
                        id2idx = self._id2idx
                        pass_idx = sorted(id2idx[id(p)] for p in delivery)
                    except Exception:
                        pass_idx = None
            if pass_idx is None:
                pass_idx = [i for i, e in enumerate(events)
                            if sub._passes(e[3])]
            self._filters[fkey] = (set(pass_idx), pass_idx)
            return self._filters[fkey]


class ServingHub:
    """The multi-tenant watch hub over one store's journal."""

    def __init__(self, store: ObjectStore, shards: int = 4,
                 admission: Optional[AdmissionController] = None,
                 poll_timeout: float = 0.5, epoch: int = 0,
                 encoder: Optional[Callable] = None):
        self.store = store
        self.admission = admission
        self.poll_timeout = poll_timeout
        # replica epoch stamped into every frame: a federated client
        # whose cursor is handed to a PEER replica's hub sees the epoch
        # change and knows the prev-chain now names a different journal
        # mirror (docs/design/federation.md)
        self.epoch = int(epoch)
        # optional wire codec ``(kind, obj) -> bytes``; when set, frames
        # carry shared per-event encoded payloads (see _BurstIndex)
        self.encoder = encoder
        self.shards = [HubShard(i, store, self)
                       for i in range(max(1, int(shards)))]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        # small cross-shard burst cache: 8 shards fetching overlapping
        # journal ranges in the same storm reuse ONE index + encoding
        # (keyed by rv coverage; entries invalidated by window checks)
        self._bursts: deque = deque(maxlen=4)
        self._burst_lock = threading.Lock()
        # bounded rolling window of per-frame fan-out latencies (ms) for
        # the bench percentiles; the histogram metric is the full record
        self.fanout_ms: deque = deque(maxlen=65536)
        self.frames_total = 0
        self.events_total = 0
        self.relists_total = 0

    # -- shared burst cache --------------------------------------------------

    def _shared_burst(self, cursor: int, head: int,
                      timeout: float) -> tuple:
        """``(burst, tail, resync)`` covering ``(cursor, tail]``. A
        cached burst is reused when it starts exactly where this shard
        needs to resume AND is still inside the journal window (a
        snapshot install or force-clear moves ``head`` past every stale
        burst, invalidating the cache for free). Reuse may serve a tail
        slightly behind the store head — the shard's next round catches
        up; what it never does is skip or reorder journal rvs."""
        with self._burst_lock:
            for b in self._bursts:
                if (b.rvs and b.rvs[0] >= head
                        and b.rvs[0] <= cursor + 1 <= b.rvs[-1]):
                    return b, b.rvs[-1], False
        events, tail, resync = self.store.events_since(cursor, timeout)
        if resync:
            return None, tail, True
        if not events:
            return None, tail, False
        burst = _BurstIndex(self.store, events)
        with self._burst_lock:
            self._bursts.appendleft(burst)
        return burst, tail, False

    def clear_bursts(self) -> None:
        """Drop cached bursts (a follower calls this after a snapshot
        install replaces the mirror wholesale)."""
        with self._burst_lock:
            self._bursts.clear()

    def set_epoch(self, epoch: int) -> None:
        """Advance the replica epoch stamped into frames (leadership
        changed underneath this replica's mirror)."""
        self.epoch = int(epoch)

    # -- subscriber lifecycle ----------------------------------------------

    def shard_of(self, client_id: str) -> HubShard:
        return self.shards[zlib.crc32(client_id.encode())
                           % len(self.shards)]

    def subscribe(self, client_id: str, tenant: str = "default",
                  kinds=None, filter_attr=None,
                  filter_fn: Optional[Callable] = None,
                  since_rv: Optional[int] = None,
                  prime: bool = True) -> Subscription:
        """Create a session. ``since_rv=None`` anchors at the journal
        tail (new events only — the list half is the client's job);
        an explicit rv replays the journal from there, or relists if it
        already fell off the window. Raises ThrottledError past the
        tenant's subscription cap."""
        if self.admission is not None:
            self.admission.acquire_subscription(tenant)
        try:
            tail = self.store.current_rv()
            cursor = tail if since_rv is None else int(since_rv)
            sub = Subscription(client_id, tenant, kinds, filter_attr,
                               filter_fn, cursor)
            sub.hub = self
            if prime and sub.filtered and cursor == tail:
                # old_p baseline: what a list-then-watch client already
                # sees passing (kind-scoped; the whole store otherwise).
                # ONLY valid when the cursor anchors exactly at the tail
                # — the store's CURRENT state is neither the view at a
                # past rv nor at a FUTURE one (a failed-over cursor ahead
                # of a lagging mirror), so both replaying and ahead
                # subscribers start from an empty baseline instead
                # (first-pass events classify as ADDED, exactly informer
                # relist semantics; an ahead cursor just holds until the
                # mirror's journal passes it).
                from ..apiserver.store import KINDS
                for kind in (sub.kinds or KINDS):
                    for o in self.store.list_refs(kind):
                        if sub._passes(o):
                            sub._passing.add((kind,
                                              self.store.key_of(kind, o)))
            self.shard_of(client_id).add(sub)
            return sub
        except BaseException:
            if self.admission is not None:
                self.admission.release_subscription(tenant)
            raise

    def unsubscribe(self, sub: Subscription) -> None:
        self.shard_of(sub.client_id).remove(sub)
        if self.admission is not None:
            self.admission.release_subscription(sub.tenant)

    def rewind(self, sub: Subscription, rv: int) -> None:
        """Client-detected frame loss: replay the journal from ``rv``
        (the client's last applied frame chain point). If ``rv`` already
        fell off the window the next dispatch relists instead."""
        shard = self.shard_of(sub.client_id)
        with shard.lock:
            sub.cursor = min(sub.cursor, int(rv))
            sub.last_framed = int(rv)

    def subscriber_count(self) -> int:
        return sum(len(s.subs) for s in self.shards)

    # -- dispatch ----------------------------------------------------------

    def pump(self) -> int:
        """Synchronous dispatch round over every shard (deterministic —
        the simulator's tick hook and the tests)."""
        return sum(shard.dispatch_once(timeout=0.0)
                   for shard in self.shards)

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for shard in self.shards:
            t = threading.Thread(target=shard.run_loop, daemon=True,
                                 name=f"hub-shard-{shard.index}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # wake the journal waiters so shard threads observe the stop
        try:
            with self.store._lock:
                self.store._journal_cond.notify_all()
        except Exception:
            pass
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    # -- accounting ----------------------------------------------------------

    def _note_frame(self, n_events: int, latency_ms: float) -> None:
        with self._lock:
            self.frames_total += 1
            self.events_total += n_events
            self.fanout_ms.append(latency_ms)
        try:
            from ..metrics import metrics as m
            m.inc(m.SERVING_BATCHES)
            m.inc(m.SERVING_EVENTS, n_events)
            m.observe(m.SERVING_FANOUT_LATENCY, latency_ms)
        except Exception:
            pass

    def _note_relist(self) -> None:
        with self._lock:
            self.relists_total += 1
        try:
            from ..metrics import metrics as m
            m.inc(m.SERVING_RELISTS)
        except Exception:
            pass

    def _note_depth(self, shard: int, depth: int,
                    backpressure: float = 0.0) -> None:
        try:
            from ..metrics import metrics as m
            m.set_gauge(m.SERVING_SHARD_DEPTH, depth, shard=str(shard))
            m.set_gauge(m.SERVING_SHARD_BACKPRESSURE,
                        round(backpressure, 4), shard=str(shard))
        except Exception:
            pass

    def fanout_percentiles(self) -> dict:
        with self._lock:
            lat = sorted(self.fanout_ms)
        if not lat:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0, "count": 0}
        at = lambda q: lat[min(len(lat) - 1, int(q * len(lat)))]
        return {"p50": round(at(0.50), 3), "p95": round(at(0.95), 3),
                "p99": round(at(0.99), 3), "count": len(lat)}

    def report(self) -> dict:
        pressures = {s.index: s.pressure() for s in self.shards}
        return {
            "epoch": self.epoch,
            "shards": len(self.shards),
            "subscribers": self.subscriber_count(),
            "shard_depths": {i: p[0] for i, p in pressures.items()},
            "shard_backpressure": {i: round(p[1], 4)
                                   for i, p in pressures.items()},
            "frames_total": self.frames_total,
            "events_total": self.events_total,
            "relists_total": self.relists_total,
            "fanout_ms": self.fanout_percentiles(),
        }
