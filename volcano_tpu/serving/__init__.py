"""Multi-tenant serving hub (docs/design/serving.md).

The apiserver/RemoteStore seam used to be a single-threaded convenience:
one long-poll thread per client, a fresh connection per write, no notion
of a tenant. This package turns that seam into a serving layer that
survives thousands of concurrent watchers:

* :mod:`.hub` — the sharded watch hub: N dispatch shards (hash by client
  id), every subscriber carrying a persistent cursor into the store's
  rv-sorted gap-free journal, coalesced event-batch frames (one delivery
  per burst), server-side kind/field filters with the PR-3 filter-flip
  lifecycle semantics, and a structured ``relist`` signal when a cursor
  falls off the journal window.
* :mod:`.admission` — tenant identity + token-bucket rate limits and
  max-subscription caps at the write/watch edge (HTTP 429 with
  Retry-After; ``volcano_serving_*`` metrics).
* :mod:`.storm` — the watcher-storm gate runner (`vcctl sim storm` /
  `make storm-smoke`): 1k+ subscribers with seeded frame-drop faults
  through a bind-flush storm, asserting cursor convergence, zero gaps,
  throttling and bit-identical double runs.

``set_active``/``serving_report`` register the process's live hub +
admission controller so the metrics server can expose them on
``/debug/serving`` without holding references through import cycles.
"""

from __future__ import annotations

_ACTIVE = {"hub": None, "admission": None}


def set_active(hub=None, admission=None) -> None:
    """Register the live hub/admission pair for /debug/serving (either
    may be None; a later call replaces only what it names)."""
    if hub is not None:
        _ACTIVE["hub"] = hub
    if admission is not None:
        _ACTIVE["admission"] = admission


def clear_active() -> None:
    _ACTIVE["hub"] = None
    _ACTIVE["admission"] = None


def serving_report() -> dict:
    """The /debug/serving payload: hub shard depths + fan-out latency
    percentiles and per-tenant admission counters, from whatever is
    registered (empty sections when nothing is)."""
    hub = _ACTIVE["hub"]
    adm = _ACTIVE["admission"]
    return {
        "hub": hub.report() if hub is not None else None,
        "admission": adm.report() if adm is not None else None,
    }
