"""Admission control at the serving edge (docs/design/serving.md).

Every request at the HTTP seam carries a ``tenant=`` identity (absent =
``"default"``). Two enforcement points:

* **writes** — a per-tenant token bucket: ``admit_write`` either spends
  a token or raises :class:`ThrottledError` carrying the bucket's
  refill horizon, which the HTTP layer maps to a structured 429 with a
  ``Retry-After`` header (and RemoteStore honors in its write backoff).
* **subscriptions** — a per-tenant cap on concurrent hub subscriptions:
  ``acquire_subscription``/``release_subscription`` bracket a
  subscriber's lifetime; the cap rejects the storm of one noisy tenant
  without starving the others (each tenant's budget is its own).

Determinism: buckets read an injectable ``now_fn`` so the simulator can
drive them off the virtual clock — double runs then throttle the exact
same requests (the same property the resync backoff relies on).
Metrics: ``volcano_serving_admitted_total`` /
``volcano_serving_throttled_total`` per tenant, mirrored in
:meth:`AdmissionController.report` for /debug/serving.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class ThrottledError(Exception):
    """Raised when a tenant exceeds its admission budget. ``retry_after``
    is the seconds the caller should wait before retrying — the HTTP
    layer surfaces it as the 429 response's Retry-After header."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = max(0.0, float(retry_after))


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second, ``burst`` capacity.
    Not thread-safe on its own — the controller serializes access."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = now

    def take(self, cost: float, now: float):
        """(allowed, retry_after_seconds)."""
        if now > self.last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
            self.last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True, 0.0
        if self.rate <= 0:
            return False, 1.0
        return False, (cost - self.tokens) / self.rate


class TenantPolicy:
    """Per-tenant limits; the controller's defaults apply where a field
    is None."""

    __slots__ = ("write_rate", "write_burst", "max_subscriptions")

    def __init__(self, write_rate: Optional[float] = None,
                 write_burst: Optional[float] = None,
                 max_subscriptions: Optional[int] = None):
        self.write_rate = write_rate
        self.write_burst = write_burst
        self.max_subscriptions = max_subscriptions


class AdmissionController:
    """Per-tenant write rate limits + subscription caps.

    Defaults are deliberately generous (a single-tenant deployment never
    notices the edge exists); per-tenant overrides carry the real
    policy. ``now_fn`` defaults to ``time.monotonic``; the simulator
    passes the virtual clock's ``now`` for deterministic throttling.
    """

    DEFAULT_WRITE_RATE = 1000.0     # tokens (writes) per second
    DEFAULT_WRITE_BURST = 2000.0
    DEFAULT_MAX_SUBSCRIPTIONS = 1024

    def __init__(self, write_rate: float = None, write_burst: float = None,
                 max_subscriptions: int = None,
                 tenants: Dict[str, TenantPolicy] = None,
                 now_fn: Callable[[], float] = None):
        self.write_rate = float(write_rate
                                if write_rate is not None
                                else self.DEFAULT_WRITE_RATE)
        self.write_burst = float(write_burst
                                 if write_burst is not None
                                 else self.DEFAULT_WRITE_BURST)
        self.max_subscriptions = int(
            max_subscriptions if max_subscriptions is not None
            else self.DEFAULT_MAX_SUBSCRIPTIONS)
        self.tenants = dict(tenants or {})
        # lint: allow(clock-discipline): injectable now_fn — the sim passes the virtual clock; the production default is monotonic ON PURPOSE (token buckets must not rewind on wall jumps)
        self.now_fn = now_fn or time.monotonic
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._subs: Dict[str, int] = {}
        self.admitted: Dict[str, int] = {}
        self.throttled: Dict[str, int] = {}

    # -- policy resolution -------------------------------------------------

    def _policy(self, tenant: str) -> tuple:
        p = self.tenants.get(tenant)
        rate = p.write_rate if p and p.write_rate is not None \
            else self.write_rate
        burst = p.write_burst if p and p.write_burst is not None \
            else self.write_burst
        cap = p.max_subscriptions if p and p.max_subscriptions is not None \
            else self.max_subscriptions
        return rate, burst, cap

    def _count(self, table: Dict[str, int], tenant: str,
               metric_name: str) -> None:
        table[tenant] = table.get(tenant, 0) + 1
        try:
            from ..metrics import metrics as m
            m.inc(metric_name, tenant=tenant)
        except Exception:
            pass

    # -- write edge --------------------------------------------------------

    def admit_write(self, tenant: str = "default", cost: float = 1.0) -> None:
        """Spend one write token or raise :class:`ThrottledError`."""
        from ..metrics.metrics import SERVING_ADMITTED, SERVING_THROTTLED
        now = self.now_fn()
        with self._lock:
            rate, burst, _ = self._policy(tenant)
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = TokenBucket(rate, burst, now)
            ok, retry_after = b.take(cost, now)
            if ok:
                self._count(self.admitted, tenant, SERVING_ADMITTED)
                return
            self._count(self.throttled, tenant, SERVING_THROTTLED)
        raise ThrottledError(
            f"tenant {tenant!r} exceeded its write rate "
            f"({rate:g}/s, burst {burst:g})", retry_after=retry_after)

    # -- watch edge --------------------------------------------------------

    def acquire_subscription(self, tenant: str = "default") -> None:
        """Claim one subscription slot or raise :class:`ThrottledError`.
        The caller MUST pair it with :meth:`release_subscription`."""
        from ..metrics.metrics import SERVING_ADMITTED, SERVING_THROTTLED
        with self._lock:
            _, _, cap = self._policy(tenant)
            held = self._subs.get(tenant, 0)
            if held >= cap:
                self._count(self.throttled, tenant, SERVING_THROTTLED)
                throttle = ThrottledError(
                    f"tenant {tenant!r} holds {held} subscriptions "
                    f"(cap {cap})", retry_after=5.0)
            else:
                self._subs[tenant] = held + 1
                self._count(self.admitted, tenant, SERVING_ADMITTED)
                return
        raise throttle

    def release_subscription(self, tenant: str = "default") -> None:
        with self._lock:
            held = self._subs.get(tenant, 0)
            if held <= 1:
                self._subs.pop(tenant, None)
            else:
                self._subs[tenant] = held - 1

    # -- observability -----------------------------------------------------

    def throttled_tenants(self) -> list:
        with self._lock:
            return sorted(t for t, n in self.throttled.items() if n > 0)

    def report(self) -> dict:
        with self._lock:
            return {
                "defaults": {"write_rate": self.write_rate,
                             "write_burst": self.write_burst,
                             "max_subscriptions": self.max_subscriptions},
                "subscriptions": dict(self._subs),
                "admitted": dict(self.admitted),
                "throttled": dict(self.throttled),
            }
