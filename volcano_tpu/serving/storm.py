"""Watcher-storm gate runner (`vcctl sim storm` / `make storm-smoke`,
docs/design/serving.md).

The scenario: the REAL scheduler churns through a seeded workload whose
resident backlog flushes a bind storm in the opening ticks, while the
serving hub fans the journal out to 1k+ subscribers across dozens of
tenants — most filtered to the scheduler's pods (the production watch
shape), some kind-scoped, some unfiltered — with THREE fault layers on:

* seeded FRAME drops between hub and client (the FlakyWatch coin idiom,
  content-keyed crc32 over the frame chain) — the client detects the
  broken frame chain and rewinds;
* a mid-storm ``force_gap`` clearing the journal — every lagging cursor
  must take the structured relist, not silently skip;
* cache-side FlakyWatch drops on the scheduler's own pod watch —
  enabled at storm scale since the fault coin re-keyed from
  resource_version to the commit-order-stable (key, per-key sequence)
  identity (sim/faults.py; the PR 11 rv-interleaving finding that used
  to confine these faults to the failover gate), with anti-entropy
  every tick so each divergence is repaired before that tick's audit.

A noisy tenant hammers the admission edge (writes past its token bucket,
subscriptions past its cap) and must be throttled without starving the
other tenants.

Gate (all checked twice — the double run must be bit-identical on bind
AND ledger fingerprints): every subscriber cursor converges to the final
store rv, zero unrecovered frame-chain gaps, >=1 relist taken, >=1
throttled tenant, coalescing ratio (events per frame) >> 1, and the
engine's own invariant catalog clean on every audited tick.
"""

from __future__ import annotations

import zlib
from typing import List, Optional

from .admission import AdmissionController, TenantPolicy, ThrottledError
from .hub import ServingHub, Subscription

# the bind-storm shape: a large resident gang backlog flushes through
# the opening cycles while Poisson arrivals + node flaps keep churning
STORM_TENANTS = 16
NOISY_TENANT = "noisy"
NOISY_WRITES_PER_TICK = 6
NOISY_WRITE_RATE = 2.0          # tokens per virtual second
NOISY_SUB_CAP = 2


def storm_config(seed: int = 43, ticks: int = 80, nodes: int = 192,
                 resident: int = 192):
    """The `make storm-smoke` churn: a resident backlog big enough that
    the opening flushes are a genuine bind storm (~1.5k binds), Poisson
    arrivals, node flaps and bind failures.

    Cache-side FlakyWatch drops run here too now: the fault coin was
    re-keyed from resource_version to the commit-order-stable (object
    key, per-key delivery sequence) identity (sim/faults.py), so the
    journal's timing-dependent rv interleaving at storm scale — the
    PR 11 finding that used to confine these faults to the failover
    gate — can no longer flip which deliveries drop. Anti-entropy runs
    every tick so each divergence is detected and repaired before that
    tick's invariant audit, exactly the failover gate's discipline."""
    from ..sim.engine import SimConfig
    from ..sim.faults import FaultConfig
    from ..sim.workload import WorkloadConfig
    return SimConfig(
        seed=seed, ticks=ticks, tick_s=1.0, n_nodes=nodes,
        node_cpu="16", node_mem="32Gi",
        resident_jobs=resident, resident_gang=8,
        workload=WorkloadConfig(
            seed=seed, horizon_s=float(ticks) * 0.7, arrival_rate=0.4,
            duration_min_s=15.0, duration_max_s=60.0),
        faults=FaultConfig(
            seed=seed, bind_fail_rate=0.01, api_latency_s=0.001,
            flap_rate=0.02, flap_down_s=6.0,
            watch_drop_rate=0.02),
        fail_rate=0.02,
        anti_entropy_every_ticks=1,
        repro_dir=".")


class StormClient:
    """One subscriber session plus the client half of the frame-chain
    contract: seeded frame drops (the fault), gap detection via the
    ``prev`` chain, recovery via ``hub.rewind``, re-anchor on ``relist``
    frames. Event application is counting + rv dedup — the gate is about
    stream integrity, not object state."""

    def __init__(self, hub: ServingHub, sub: Subscription, seed: int,
                 drop_rate: float):
        self.hub = hub
        self.sub = sub
        self.seed = seed
        self.drop_rate = drop_rate
        self.faults_on = True
        self.applied = sub.last_framed   # frame-chain position
        self.events_applied = 0
        self.frames_applied = 0
        self.frames_dropped = 0
        self.gaps_detected = 0
        self.gaps_unrecovered = 0
        self.relists = 0

    def _drop(self, frame: dict) -> bool:
        if not self.faults_on or self.drop_rate <= 0:
            return False
        h = zlib.crc32(f"{self.sub.client_id}:{frame.get('prev')}:"
                       f"{frame.get('to_rv', frame.get('rv'))}:"
                       f"{self.seed}".encode())
        return (h % 10_000) / 10_000.0 < self.drop_rate

    def drain(self) -> None:
        for frame in self.sub.take_frames():
            if frame.get("relist"):
                # structured re-anchor: the client re-lists (modeled as
                # accepting the snapshot) and resumes from rv
                self.applied = int(frame["rv"])
                self.relists += 1
                continue
            if self._drop(frame):
                self.frames_dropped += 1
                continue   # silent loss: detected by the NEXT frame
            if int(frame["prev"]) != self.applied:
                # broken chain: a frame before this one was lost —
                # rewind the cursor to the last applied position and
                # discard the rest of this drain (it replays)
                self.gaps_detected += 1
                self.hub.rewind(self.sub, self.applied)
                break
            for rv, _action, _kind, _o in frame["events"]:
                if rv > self.applied:
                    self.events_applied += 1
            self.applied = int(frame["to_rv"])
            self.frames_applied += 1

    def converged(self, final_rv: int) -> bool:
        """Converged = the hub walked this session's cursor to the final
        rv AND the client applied every frame the hub framed for it (no
        chain position outstanding). A cursor can pass rvs the filter
        delivered nothing for — the client legitimately never sees those
        — so convergence is the pair, not a client-side rv race."""
        return self.sub.cursor >= final_rv \
            and self.applied == self.sub.last_framed


def _build_clients(hub: ServingHub, n: int, seed: int,
                   drop_rate: float) -> List[StormClient]:
    """Deterministic subscriber population: ~70% filtered to the
    scheduler's pods (the production informer shape), ~15% node-scoped,
    the rest unfiltered firehose consumers. Tenants round-robin over
    STORM_TENANTS, with a slice owned by the noisy tenant so its
    throttling is observable on a real population."""
    clients: List[StormClient] = []
    for i in range(n):
        cid = f"watch-{i:05d}"
        tenant = NOISY_TENANT if i % 97 == 0 \
            else f"tenant-{i % STORM_TENANTS}"
        kinds = filter_attr = None
        r = i % 20
        if r < 14:
            kinds = ("pods",)
            filter_attr = (("spec", "scheduler_name"), "volcano")
        elif r < 17:
            kinds = ("nodes",)
        try:
            sub = hub.subscribe(cid, tenant=tenant, kinds=kinds,
                                filter_attr=filter_attr, since_rv=0)
        except ThrottledError:
            continue   # the noisy tenant's cap kicking in IS the test
        clients.append(StormClient(hub, sub, seed ^ (i * 2654435761),
                                   drop_rate))
    return clients


def run_storm(seed: int = 43, ticks: int = 80, nodes: int = 192,
              subscribers: int = 1000, shards: int = 8,
              drop_rate: float = 0.03,
              gap_tick: Optional[int] = None,
              resident: int = 192) -> dict:
    """One full storm run. Returns the flat verdict dict the CLI gates
    on (`checks` all-true = pass); see the module docstring for what
    each check means."""
    from ..sim.engine import SimEngine
    from ..sim.faults import FlakyWatch
    cfg = storm_config(seed=seed, ticks=ticks, nodes=nodes,
                       resident=resident)
    eng = SimEngine(cfg)
    admission = AdmissionController(
        tenants={NOISY_TENANT: TenantPolicy(
            write_rate=NOISY_WRITE_RATE, write_burst=NOISY_WRITE_RATE,
            max_subscriptions=NOISY_SUB_CAP)},
        now_fn=eng.clock.now)
    hub = ServingHub(eng.store, shards=shards, admission=admission)
    clients = _build_clients(hub, subscribers, seed, drop_rate)
    sub_throttles = admission.throttled.get(NOISY_TENANT, 0)
    if gap_tick is None:
        gap_tick = max(2, ticks // 2)
    noisy_throttled_writes = [0]

    def tick_hook(tick: int) -> None:
        if tick == gap_tick:
            # the journal window rolls past every cursor: the next
            # dispatch must take the structured relist, not skip events
            FlakyWatch.force_gap(eng.store)
        # the noisy tenant's write traffic at the admission edge (its
        # bucket refills off the virtual clock: deterministic verdicts)
        for _ in range(NOISY_WRITES_PER_TICK):
            try:
                admission.admit_write(NOISY_TENANT)
            except ThrottledError:
                noisy_throttled_writes[0] += 1
        hub.pump()
        for c in clients:
            c.drain()

    eng.tick_hooks.append(tick_hook)
    result = eng.run()

    # settle: the storm is over, the faults stop, everyone must converge
    # — lagging clients rewind/relist their way to the final rv
    final_rv = eng.store.current_rv()
    for c in clients:
        c.faults_on = False
    for _ in range(64):
        hub.pump()
        for c in clients:
            c.drain()
        if all(c.converged(final_rv) for c in clients):
            break
        for c in clients:
            # a broken chain (lost frame never followed by another) only
            # heals by rewinding; a merely-lagging cursor just needs the
            # next pump
            if c.applied != c.sub.last_framed:
                hub.rewind(c.sub, c.applied)
    converged = sum(1 for c in clients if c.converged(final_rv))
    unrecovered = sum(c.gaps_unrecovered for c in clients) \
        + sum(1 for c in clients if not c.converged(final_rv))
    coalesce_ratio = hub.events_total / max(1, hub.frames_total)
    summary = result.summary()
    verdict = {
        "storm": summary,
        "final_rv": final_rv,
        "subscribers": len(clients),
        "converged": converged,
        "gaps_detected": sum(c.gaps_detected for c in clients),
        "gaps_unrecovered": unrecovered,
        "frames_dropped": sum(c.frames_dropped for c in clients),
        "frames_total": hub.frames_total,
        "events_total": hub.events_total,
        "coalesce_ratio": round(coalesce_ratio, 1),
        "relists": hub.relists_total,
        "throttled": dict(admission.throttled),
        "noisy_throttled_writes": noisy_throttled_writes[0],
        "noisy_subscription_throttles": sub_throttles,
        "fanout_ms": hub.fanout_percentiles(),
        "bind_fingerprint": result.bind_fingerprint(),
        "ledger_fingerprint": result.ledger.get("fingerprint"),
        "violations": len(result.violations),
        "watch_drops": result.watch_drops,
        "divergence_repairs": result.divergence_repairs,
    }
    return verdict
