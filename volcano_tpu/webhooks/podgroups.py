"""PodGroup admission: /podgroups/mutate — default queue
(reference: pkg/webhooks/admission/podgroups/mutate/mutate_podgroup.go:95-110).
"""

from __future__ import annotations

from ..models import objects as obj
from ..models.objects import PodGroup
from .router import AdmissionService, register_admission


def mutate_podgroup(store, operation, pg: PodGroup, old=None) -> None:
    if not pg.spec.queue:
        pg.spec.queue = obj.DEFAULT_QUEUE


register_admission(AdmissionService(
    path="/podgroups/mutate", kind="podgroups", operations=("CREATE",),
    mutate=mutate_podgroup))
