"""Shared validation helpers for admission webhooks
(reference: pkg/webhooks/admission/jobs/validate/util.go and k8s validation).
"""

from __future__ import annotations

import re
from typing import List, Optional

from ..models.objects import JobAction, JobEvent, LifecyclePolicy

DNS1123_LABEL_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
DNS1123_LABEL_MAX = 63
POD_NAME_MAX = 253

# events/actions allowed in user-facing lifecycle policies (util.go:32-57)
POLICY_EVENTS = {
    JobEvent.ANY: True,
    JobEvent.POD_FAILED: True,
    JobEvent.POD_EVICTED: True,
    JobEvent.JOB_UNKNOWN: True,
    JobEvent.TASK_COMPLETED: True,
    JobEvent.TASK_FAILED: True,
    JobEvent.OUT_OF_SYNC: False,
    JobEvent.COMMAND_ISSUED: False,
    JobEvent.JOB_UPDATED: True,
}
POLICY_ACTIONS = {
    JobAction.ABORT_JOB: True,
    JobAction.RESTART_JOB: True,
    JobAction.RESTART_TASK: True,
    JobAction.TERMINATE_JOB: True,
    JobAction.COMPLETE_JOB: True,
    JobAction.RESUME_JOB: True,
    JobAction.SYNC_JOB: False,
    JobAction.ENQUEUE_JOB: False,
    JobAction.SYNC_QUEUE: False,
    JobAction.OPEN_QUEUE: False,
    JobAction.CLOSE_QUEUE: False,
}


def valid_events() -> List[str]:
    return [e for e, ok in POLICY_EVENTS.items() if ok]


def valid_actions() -> List[str]:
    return [a for a, ok in POLICY_ACTIONS.items() if ok]


def is_dns1123_label(value: str) -> bool:
    return len(value) <= DNS1123_LABEL_MAX and bool(DNS1123_LABEL_RE.match(value))


def validate_policies(policies: List[LifecyclePolicy]) -> Optional[str]:
    """util.go:59-115 — one error message or None."""
    seen_events = set()
    seen_exit_codes = set()
    for policy in policies:
        has_event = bool(policy.event) or bool(policy.events)
        if has_event and policy.exit_code is not None:
            return "must not specify event and exitCode simultaneously"
        if not has_event and policy.exit_code is None:
            return "either event and exitCode should be specified"
        if has_event:
            events = list(policy.events)
            if policy.event:
                events.append(policy.event)
            for event in events:
                if not POLICY_EVENTS.get(event, False):
                    return f"invalid policy event: {event}"
                if not POLICY_ACTIONS.get(policy.action, False):
                    return f"invalid policy action: {policy.action}"
                if event in seen_events:
                    return f"duplicate event {event} across different policy"
                seen_events.add(event)
        else:
            if policy.exit_code == 0:
                return "0 is not a valid error code"
            if policy.exit_code in seen_exit_codes:
                return f"duplicate exitCode {policy.exit_code}"
            seen_exit_codes.add(policy.exit_code)
    return None


def validate_int_percentage_str(key: str, value: str) -> Optional[str]:
    """admit_pod.go:183-205 — positive int or 1%-99% percentage."""
    v = value.strip()
    if v.endswith("%"):
        try:
            pct = int(v[:-1])
        except ValueError:
            return f"invalid value {value!r} for {key}"
        if pct <= 0 or pct >= 100:
            return (f"invalid value {value!r} for {key}, it must be a valid "
                    f"percentage which between 1% ~ 99%")
        return None
    try:
        iv = int(v)
    except ValueError:
        return f"invalid value {value!r} for {key}"
    if iv <= 0:
        return f"invalid value {value!r} for {key}, it must be a positive integer"
    return None
