"""Pod admission: /pods/validate + /pods/mutate
(reference: pkg/webhooks/admission/pods/{validate/admit_pod.go,
mutate/mutate_pod.go}).

Validation gates bare pods whose PodGroup is still Pending (so vanilla pods
respect gang admission) and checks disruption-budget annotations. Mutation
applies resource-group config: node selectors, tolerations and scheduler
name per group (the `--admission-conf` resourceGroups file).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..controllers.podgroup import generate_podgroup_name
from ..models import objects as obj
from ..models.objects import Pod, PodGroupPhase, Toleration
from .router import AdmissionDenied, AdmissionService, register_admission
from .util import validate_int_percentage_str

SCHEDULER_NAME = obj.DEFAULT_SCHEDULER_NAME


# -- validate (admit_pod.go:105-180) ----------------------------------------

def validate_pod(store, operation, pod: Pod, old=None) -> None:
    if pod.spec.scheduler_name != SCHEDULER_NAME:
        return
    pg_name = pod.metadata.annotations.get(obj.GROUP_NAME_ANNOTATION, "")
    if pg_name:
        _check_pg_phase(store, pod, pg_name, is_vc_job=True)
        return
    _check_pg_phase(store, pod, generate_podgroup_name(pod), is_vc_job=False)
    _validate_annotations(pod)


def _check_pg_phase(store, pod: Pod, pg_name: str, is_vc_job: bool) -> None:
    pg = store.get("podgroups", pg_name, pod.metadata.namespace)
    if pg is None:
        if is_vc_job:
            raise AdmissionDenied(
                f"failed to get PodGroup for pod "
                f"<{pod.metadata.key()}>: {pg_name} not found")
        return
    if pg.status.phase == PodGroupPhase.PENDING:
        raise AdmissionDenied(
            f"failed to create pod <{pod.metadata.key()}> as the podgroup "
            f"phase is Pending")


def _validate_annotations(pod: Pod) -> None:
    """admit_pod.go:156-181 — at most one JDB annotation, valid int/percent."""
    keys = (obj.JDB_MIN_AVAILABLE_KEY, obj.JDB_MAX_UNAVAILABLE_KEY)
    found = 0
    for key in keys:
        value = pod.metadata.annotations.get(key)
        if value is not None:
            found += 1
            err = validate_int_percentage_str(key, value)
            if err:
                raise AdmissionDenied(err)
    if found > 1:
        raise AdmissionDenied(
            f"not allow configure multiple annotations <{keys}> at same time")


# -- mutate (mutate_pod.go:100-170) -----------------------------------------

@dataclass
class ResGroupConfig:
    """One resourceGroup entry of the admission config
    (pkg/webhooks/config/admission_conf.go)."""
    resource_group: str = ""
    object_key: Dict[str, List[str]] = field(default_factory=dict)  # e.g. {"namespace": [...]} or {"annotation-key/value": [...]}
    labels: Dict[str, str] = field(default_factory=dict)            # node selector to apply
    tolerations: List[Toleration] = field(default_factory=list)
    scheduler_name: str = ""


_res_groups: List[ResGroupConfig] = []


def set_resource_groups(groups: List[ResGroupConfig]) -> None:
    """Install the admission config (the --admission-conf file equivalent)."""
    global _res_groups
    _res_groups = list(groups)


def _belongs(pod: Pod, group: ResGroupConfig) -> bool:
    """mutate_pod.go IsBelongResGroup: namespace or annotation match."""
    namespaces = group.object_key.get("namespace", [])
    if namespaces and pod.metadata.namespace in namespaces:
        return True
    ann = group.object_key.get("annotation", {})
    if isinstance(ann, dict):
        for k, v in ann.items():
            if pod.metadata.annotations.get(k) == v:
                return True
    return False


def mutate_pod(store, operation, pod: Pod, old=None) -> None:
    for group in _res_groups:
        if not _belongs(pod, group):
            continue
        if group.labels:
            pod.spec.node_selector.update(group.labels)
        if group.tolerations:
            pod.spec.tolerations.extend(group.tolerations)
        if group.scheduler_name:
            pod.spec.scheduler_name = group.scheduler_name
        return


register_admission(AdmissionService(
    path="/pods/validate", kind="pods", operations=("CREATE",),
    validate=validate_pod))
register_admission(AdmissionService(
    path="/pods/mutate", kind="pods", operations=("CREATE",),
    mutate=mutate_pod))
