"""Queue admission: /queues/validate + /queues/mutate
(reference: pkg/webhooks/admission/queues/{validate/validate_queue.go,
mutate/mutate_queue.go}).
"""

from __future__ import annotations

from ..models import objects as obj
from ..models.objects import Queue, QueueState
from .router import AdmissionDenied, AdmissionService, register_admission


def validate_queue(store, operation, queue: Queue, old=None) -> None:
    if operation == "DELETE":
        _validate_queue_deleting(store, old)
        return
    _validate_state(queue)
    if queue.spec.weight <= 0:
        raise AdmissionDenied("queue weight must be a positive integer")
    _validate_hierarchy(store, queue)


def _validate_state(queue: Queue) -> None:
    """validate_queue.go:170-189 — only Open/Closed may be requested."""
    state = queue.status.state
    if state and state not in (QueueState.OPEN, QueueState.CLOSED):
        raise AdmissionDenied(
            f"queue state must be in "
            f"{[QueueState.OPEN, QueueState.CLOSED]}")


def _validate_hierarchy(store, queue: Queue) -> None:
    """validate_queue.go:111-168"""
    hierarchy = queue.metadata.annotations.get(obj.QUEUE_HIERARCHY_ANNOTATION, "")
    weights = queue.metadata.annotations.get(
        obj.QUEUE_HIERARCHY_WEIGHT_ANNOTATION, "")
    if not hierarchy and not weights:
        return
    paths = hierarchy.split("/")
    weight_parts = weights.split("/")
    if len(paths) != len(weight_parts):
        raise AdmissionDenied(
            f"{obj.QUEUE_HIERARCHY_ANNOTATION} must have the same length "
            f"with {obj.QUEUE_HIERARCHY_WEIGHT_ANNOTATION}")
    for w in weight_parts:
        try:
            wf = float(w)
        except ValueError:
            raise AdmissionDenied(
                f"{w} in the {weights} is invalid number")
        if wf <= 0:
            raise AdmissionDenied(
                f"{w} in the {weights} must be larger than 0")
    # a queue must not sit on the path prefix of another queue's hierarchy
    for other in store.list("queues"):
        other_hierarchy = other.metadata.annotations.get(
            obj.QUEUE_HIERARCHY_ANNOTATION, "")
        if other_hierarchy and other.metadata.name != queue.metadata.name and \
                other_hierarchy.startswith(hierarchy):
            raise AdmissionDenied(
                f"{hierarchy} is not allowed to be in the sub path of "
                f"{other_hierarchy} of queue {other.metadata.name}")


def _validate_queue_deleting(store, queue: Queue) -> None:
    """validate_queue.go:199-214 — default queue protected; must be Closed."""
    if queue.metadata.name == "default":
        raise AdmissionDenied("`default` queue can not be deleted")
    if queue.status.state != QueueState.CLOSED:
        raise AdmissionDenied(
            f"only queue with state `{QueueState.CLOSED}` can be deleted, "
            f"queue `{queue.metadata.name}` state is `{queue.status.state}`")


def mutate_queue(store, operation, queue: Queue, old=None) -> None:
    """mutate_queue.go:99-137 — root-prefix hierarchy + weight default."""
    hierarchy = queue.metadata.annotations.get(obj.QUEUE_HIERARCHY_ANNOTATION, "")
    weights = queue.metadata.annotations.get(
        obj.QUEUE_HIERARCHY_WEIGHT_ANNOTATION, "")
    if hierarchy and weights and not hierarchy.startswith("root"):
        queue.metadata.annotations[obj.QUEUE_HIERARCHY_ANNOTATION] = \
            f"root/{hierarchy}"
        queue.metadata.annotations[obj.QUEUE_HIERARCHY_WEIGHT_ANNOTATION] = \
            f"1/{weights}"
    if queue.spec.weight == 0:
        queue.spec.weight = 1


register_admission(AdmissionService(
    path="/queues/mutate", kind="queues", operations=("CREATE",),
    mutate=mutate_queue))
register_admission(AdmissionService(
    path="/queues/validate", kind="queues",
    operations=("CREATE", "UPDATE", "DELETE"), validate=validate_queue))
