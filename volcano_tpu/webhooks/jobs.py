"""Job admission: /jobs/validate + /jobs/mutate
(reference: pkg/webhooks/admission/jobs/{validate/admit_job.go,
mutate/mutate_job.go}).
"""

from __future__ import annotations

import copy

from ..controllers.job import plugins as job_plugins
from ..controllers.apis import make_pod_name
from ..models import objects as obj
from ..models.objects import Job, QueueState
from .router import AdmissionDenied, AdmissionService, register_admission
from .util import (POD_NAME_MAX, is_dns1123_label, valid_actions, valid_events,
                   validate_policies)

DEFAULT_MAX_RETRY = 3
DEFAULT_TASK_NAME = "default"


# -- mutate (mutate_job.go:105-167) -----------------------------------------

def mutate_job(store, operation, job: Job, old=None) -> None:
    if not job.spec.queue:
        job.spec.queue = obj.DEFAULT_QUEUE
    if not job.spec.scheduler_name:
        job.spec.scheduler_name = obj.DEFAULT_SCHEDULER_NAME
    if job.spec.max_retry == 0:
        job.spec.max_retry = DEFAULT_MAX_RETRY
    for i, task in enumerate(job.spec.tasks):
        if not task.name:
            task.name = f"{DEFAULT_TASK_NAME}{i}"
    if job.spec.min_available == 0:
        job.spec.min_available = sum(
            t.min_available if t.min_available is not None else t.replicas
            for t in job.spec.tasks)


# -- validate (admit_job.go:110-252) ----------------------------------------

def validate_job(store, operation, job: Job, old=None) -> None:
    if operation == "UPDATE":
        _validate_job_update(old, job)
        return
    msgs = []
    if job.spec.min_available < 0:
        raise AdmissionDenied("job 'minAvailable' must be >= 0.")
    if job.spec.max_retry < 0:
        raise AdmissionDenied("'maxRetry' cannot be less than zero.")
    if job.spec.ttl_seconds_after_finished is not None and \
            job.spec.ttl_seconds_after_finished < 0:
        raise AdmissionDenied("'ttlSecondsAfterFinished' cannot be less than zero.")
    if not job.spec.tasks:
        raise AdmissionDenied("No task specified in job spec")

    task_names = set()
    total_replicas = 0
    for index, task in enumerate(job.spec.tasks):
        if task.replicas < 0:
            msgs.append(f"'replicas' < 0 in task: {task.name};")
        if task.min_available is not None and task.min_available > task.replicas:
            msgs.append(f"'minAvailable' is greater than 'replicas' in task: "
                        f"{task.name}, job: {job.metadata.name}")
        total_replicas += task.replicas
        if not is_dns1123_label(task.name):
            msgs.append(f"task name {task.name!r} must be a valid DNS-1123 label;")
        if task.name in task_names:
            msgs.append(f"duplicated task name {task.name};")
            break
        task_names.add(task.name)
        err = validate_policies(task.policies)
        if err:
            msgs.append(f"{err} valid events are {valid_events()}, "
                        f"valid actions are {valid_actions()}")
        pod_name = make_pod_name(job.metadata.name, task.name, index)
        if len(pod_name) > POD_NAME_MAX:
            msgs.append(f"pod name {pod_name!r} too long (max {POD_NAME_MAX});")
        if not task.template.spec.containers:
            msgs.append(f"no container specified in task {task.name!r} template;")

    if not is_dns1123_label(job.metadata.name):
        msgs.append(f"job name {job.metadata.name!r} must be a valid DNS-1123 label;")
    if total_replicas < job.spec.min_available:
        msgs.append("job 'minAvailable' should not be greater than "
                    "total replicas in tasks;")
    err = validate_policies(job.spec.policies)
    if err:
        msgs.append(f"{err} valid events are {valid_events()}, "
                    f"valid actions are {valid_actions()};")
    for name in job.spec.plugins:
        if not job_plugins.plugin_exists(name):
            msgs.append(f"unable to find job plugin: {name}")
    for volume in job.spec.volumes:
        if not volume.get("mount_path"):
            msgs.append("mountPath is required in volume;")

    queue = store.get("queues", job.spec.queue)
    if queue is None:
        msgs.append(f"unable to find job queue: {job.spec.queue}")
    elif queue.status.state != QueueState.OPEN:
        msgs.append(f"can only submit job to queue with state `Open`, "
                    f"queue `{queue.metadata.name}` status is "
                    f"`{queue.status.state}`")

    if msgs:
        raise AdmissionDenied(" ".join(msgs))


def _validate_job_update(old: Job, new: Job) -> None:
    """admit_job.go:210-252 — only minAvailable and tasks[*].replicas may
    change."""
    total_replicas = 0
    for task in new.spec.tasks:
        if task.replicas < 0:
            raise AdmissionDenied(f"'replicas' must be >= 0 in task: {task.name}")
        if task.min_available is not None and task.min_available > task.replicas:
            raise AdmissionDenied(
                f"'minAvailable' must be <= 'replicas' in task: {task.name};")
        total_replicas += task.replicas
    if new.spec.min_available > total_replicas:
        raise AdmissionDenied(
            "job 'minAvailable' must not be greater than total replicas")
    if new.spec.min_available < 0:
        raise AdmissionDenied("job 'minAvailable' must be >= 0")
    if len(old.spec.tasks) != len(new.spec.tasks):
        raise AdmissionDenied("job updates may not add or remove tasks")

    # neutralize the mutable fields, then require deep equality
    new_spec = copy.deepcopy(new.spec)
    old_spec = copy.deepcopy(old.spec)
    new_spec.min_available = old_spec.min_available
    new_spec.priority_class_name = old_spec.priority_class_name
    for i in range(len(new_spec.tasks)):
        new_spec.tasks[i].replicas = old_spec.tasks[i].replicas
        new_spec.tasks[i].min_available = old_spec.tasks[i].min_available
    for spec in (new_spec, old_spec):
        for volume in spec.volumes:
            if volume.get("volume_claim") is not None:
                volume["volume_claim_name"] = ""
    if new_spec != old_spec:
        raise AdmissionDenied(
            "job updates may not change fields other than `minAvailable`, "
            "`tasks[*].replicas under spec`")


register_admission(AdmissionService(
    path="/jobs/mutate", kind="jobs", operations=("CREATE",), mutate=mutate_job))
register_admission(AdmissionService(
    path="/jobs/validate", kind="jobs", operations=("CREATE", "UPDATE"),
    validate=validate_job))
