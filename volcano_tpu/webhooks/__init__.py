"""Admission webhooks (reference: pkg/webhooks).

Importing this package registers every admission service (the reference's
init()-time router.RegisterAdmission); construct a :class:`WebhookManager`
over a store to enable them, optionally restricted via the
``enabled_admission`` path list (the --enabled-admission flag).
"""

from . import jobs, podgroups, pods, queues  # noqa: F401  (register services)
from .pods import ResGroupConfig, set_resource_groups
from .router import (AdmissionDenied, AdmissionService, WebhookManager,
                     all_services, get_service, register_admission)

__all__ = [
    "AdmissionDenied", "AdmissionService", "WebhookManager", "all_services",
    "get_service", "register_admission", "ResGroupConfig",
    "set_resource_groups",
]
