"""Admission router: service registry + manager
(reference: pkg/webhooks/router/{interface,admission,server}.go and
cmd/webhook-manager/app/server.go).

An ``AdmissionService`` declares a path, the kind/operations it covers, and
mutate/validate callables. The ``WebhookManager`` (the vc-webhook-manager
process equivalent) registers every enabled service as an admission hook on
the in-process store — the store's admission chain plays the role of the
apiserver calling out to the webhook's TLS endpoint.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ..apiserver.store import AdmissionError, AdmissionHook, ObjectStore


class AdmissionDenied(AdmissionError):
    """A validating webhook rejected the object."""


@dataclass
class AdmissionService:
    """interface.go:38-48"""
    path: str
    kind: str
    operations: Sequence[str] = ("CREATE",)
    # mutate(store, operation, new_obj, old_obj) edits new_obj in place
    mutate: Optional[Callable] = None
    # validate(store, operation, new_obj, old_obj) raises AdmissionDenied
    validate: Optional[Callable] = None


_services: Dict[str, AdmissionService] = {}


def register_admission(service: AdmissionService) -> None:
    """router.RegisterAdmission equivalent (each webhook file's init())."""
    _services[service.path] = service


def get_service(path: str) -> Optional[AdmissionService]:
    return _services.get(path)


def all_services() -> List[AdmissionService]:
    return list(_services.values())


def enabled_services(enabled_admission: Optional[str]):
    """Filter registered services by the --enabled-admission flag
    (None enables all) — shared by the in-process manager and the
    multi-process admission endpoint."""
    if enabled_admission is None:
        enabled = None
    else:
        enabled = {p.strip() for p in enabled_admission.split(",")
                   if p.strip()}
    return [s for s in all_services()
            if enabled is None or s.path in enabled]


class WebhookManager:
    """Registers enabled admission services with the store
    (cmd/webhook-manager/app/server.go:64-87 registers webhook
    configurations with the apiserver)."""

    def __init__(self, store: ObjectStore,
                 enabled_admission: Optional[str] = None):
        """enabled_admission: comma-separated service paths
        (the --enabled-admission flag); None enables all."""
        self.store = store
        self.services: List[AdmissionService] = \
            enabled_services(enabled_admission)
        self._hooks: List[AdmissionHook] = []
        for svc in self.services:
            hook = AdmissionHook(
                kind=svc.kind, path=svc.path,
                mutate=self._bind(svc.mutate), validate=self._bind(svc.validate),
                operations=tuple(svc.operations))
            self._hooks.append(hook)
            store.register_admission(hook)

    def _bind(self, fn):
        if fn is None:
            return None
        store = self.store

        def bound(operation, new_obj, old_obj):
            return fn(store, operation, new_obj, old_obj)
        return bound


class AdmissionHTTPServer:
    """The webhook-manager's serving half in multi-process mode: exposes
    the enabled admission services over HTTPS and self-registers them —
    with the CA bundle — with a remote apiserver, which calls back per
    matching operation, verifying the serving certificate against that
    bundle (cmd/webhook-manager/app/server.go:64-87 + util.go:37-130 +
    router/server.go).

    ``tls_cert_dir``: directory for the self-signed CA + CA-signed serving
    pair (generated on first start, utils/certs.py); ``None`` serves plain
    HTTP (the --insecure-http escape hatch).

    Request:  POST <service path> {"operation", "object", "old"}
    Response: {"allowed": bool, "message": str, "object": mutated-or-null}
    """

    def __init__(self, store, enabled_admission: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tls_cert_dir: Optional[str] = None):
        import json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ..apiserver.codec import decode_object, encode_object

        self.services: Dict[str, AdmissionService] = {
            s.path: s for s in enabled_services(enabled_admission)}
        self.host = host
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                svc = outer.services.get(self.path)
                if svc is None:
                    return self._send(404, {"allowed": False,
                                            "message": "unknown path"})
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))).decode())
                new_obj = decode_object(svc.kind, body["object"]) \
                    if body.get("object") else None
                old_obj = decode_object(svc.kind, body["old"]) \
                    if body.get("old") else None
                op = body.get("operation", "CREATE")
                try:
                    if svc.mutate is not None:
                        svc.mutate(store, op, new_obj, old_obj)
                    if svc.validate is not None:
                        svc.validate(store, op, new_obj, old_obj)
                except AdmissionError as e:
                    return self._send(200, {"allowed": False,
                                            "message": str(e)})
                return self._send(200, {
                    "allowed": True, "message": "",
                    "object": encode_object(svc.kind, new_obj)
                    if new_obj is not None else None})

            def _send(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class TLSServer(ThreadingHTTPServer):
            """Handshake runs in the per-request thread, NOT the accept
            loop: wrapping the listening socket would let one stalled
            client park accept() inside do_handshake and block every
            admission callback cluster-wide (fail-closed means all writes
            rejected)."""

            ssl_context = None

            def finish_request(self, request, client_address):
                if self.ssl_context is not None:
                    request.settimeout(10.0)   # bound a stalled handshake
                    try:
                        request = self.ssl_context.wrap_socket(
                            request, server_side=True)
                    except OSError:
                        return   # bad handshake: drop this connection only
                    request.settimeout(None)
                super().finish_request(request, client_address)

        self.scheme = "http"
        self.ca_bundle: Optional[str] = None
        if tls_cert_dir is not None:
            import ssl

            from ..utils.certs import ensure_webhook_certs, read_pem
            ca_crt, tls_crt, tls_key = ensure_webhook_certs(
                tls_cert_dir, hosts=(host, "localhost"))
            # stdlib-hardened server defaults (TLS >= 1.2, vetted ciphers)
            ctx = ssl.create_default_context(ssl.Purpose.CLIENT_AUTH)
            ctx.load_cert_chain(tls_crt, tls_key)
            self.httpd = TLSServer((host, port), Handler)
            self.httpd.ssl_context = ctx
            self.scheme = "https"
            self.ca_bundle = read_pem(ca_crt)
        else:
            self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port

    def start(self):
        import threading
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True,
                             name="webhook-admission-server")
        t.start()
        return t

    def stop(self):
        self.httpd.shutdown()

    def register_with(self, apiserver_url: str) -> None:
        """Self-register every service — CA bundle included — with the
        remote apiserver (the reference registers Validating/Mutating
        WebhookConfigurations carrying caBundle, util.go:37-101)."""
        import json
        import urllib.request
        for svc in self.services.values():
            payload = {"kind": svc.kind, "path": svc.path,
                       "operations": list(svc.operations),
                       "url": f"{self.scheme}://{self.host}:{self.port}"
                              f"{svc.path}"}
            if self.ca_bundle is not None:
                payload["ca_bundle"] = self.ca_bundle
            req = urllib.request.Request(
                f"{apiserver_url.rstrip('/')}/admissionwebhooks",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            urllib.request.urlopen(req, timeout=10.0).close()
