"""Admission router: service registry + manager
(reference: pkg/webhooks/router/{interface,admission,server}.go and
cmd/webhook-manager/app/server.go).

An ``AdmissionService`` declares a path, the kind/operations it covers, and
mutate/validate callables. The ``WebhookManager`` (the vc-webhook-manager
process equivalent) registers every enabled service as an admission hook on
the in-process store — the store's admission chain plays the role of the
apiserver calling out to the webhook's TLS endpoint.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..apiserver.store import AdmissionError, AdmissionHook, ObjectStore


class AdmissionDenied(AdmissionError):
    """A validating webhook rejected the object."""


@dataclass
class AdmissionService:
    """interface.go:38-48"""
    path: str
    kind: str
    operations: Sequence[str] = ("CREATE",)
    # mutate(store, operation, new_obj, old_obj) edits new_obj in place
    mutate: Optional[Callable] = None
    # validate(store, operation, new_obj, old_obj) raises AdmissionDenied
    validate: Optional[Callable] = None


_services: Dict[str, AdmissionService] = {}


def register_admission(service: AdmissionService) -> None:
    """router.RegisterAdmission equivalent (each webhook file's init())."""
    _services[service.path] = service


def get_service(path: str) -> Optional[AdmissionService]:
    return _services.get(path)


def all_services() -> List[AdmissionService]:
    return list(_services.values())


class WebhookManager:
    """Registers enabled admission services with the store
    (cmd/webhook-manager/app/server.go:64-87 registers webhook
    configurations with the apiserver)."""

    def __init__(self, store: ObjectStore,
                 enabled_admission: Optional[str] = None):
        """enabled_admission: comma-separated service paths
        (the --enabled-admission flag); None enables all."""
        self.store = store
        if enabled_admission is None:
            enabled = None
        else:
            enabled = {p.strip() for p in enabled_admission.split(",") if p.strip()}
        self.services: List[AdmissionService] = [
            s for s in all_services()
            if enabled is None or s.path in enabled]
        self._hooks: List[AdmissionHook] = []
        for svc in self.services:
            hook = AdmissionHook(
                kind=svc.kind, path=svc.path,
                mutate=self._bind(svc.mutate), validate=self._bind(svc.validate),
                operations=tuple(svc.operations))
            self._hooks.append(hook)
            store.register_admission(hook)

    def _bind(self, fn):
        if fn is None:
            return None
        store = self.store

        def bound(operation, new_obj, old_obj):
            return fn(store, operation, new_obj, old_obj)
        return bound
