from .store import AdmissionError, AdmissionHook, ObjectStore  # noqa: F401
