"""In-process object store + watch bus.

The reference's distributed backbone is the Kubernetes API server: informer
watch streams in, binding/eviction/status writes out (SURVEY.md section 5.8).
In this standalone framework the same role is played by this store: typed
object collections with resource versions, admission hook chains (the webhook
manager registers here), and synchronous watch fan-out to informers (cache,
controllers).

Kinds and scoping mirror the reference's CRD groups plus the consumed core
slice; namespaced kinds key by "namespace/name", cluster-scoped by "name".
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional

from ..models import objects as obj
from ..utils.clock import GLOBAL_CLOCK, Clock
from ..utils.fastclone import fast_clone

NAMESPACED = {"pods", "podgroups", "jobs", "commands", "resourcequotas", "services",
              "configmaps", "secrets", "networkpolicies", "persistentvolumeclaims"}
CLUSTER_SCOPED = {"nodes", "queues", "priorityclasses", "numatopologies",
                  "persistentvolumes"}
KINDS = NAMESPACED | CLUSTER_SCOPED


class AdmissionError(Exception):
    """Raised when a validating admission hook rejects an operation."""


class ConflictError(Exception):
    """Raised on update when the caller's copy is stale (optimistic
    concurrency, the apiserver 409). Re-get and retry."""


class AdmissionHook:
    """One admission service (reference: pkg/webhooks/router/interface.go:38-48).

    ``mutate``/``validate`` receive (operation, new_obj, old_obj) where
    operation is "CREATE"|"UPDATE"|"DELETE"; mutate edits new_obj in place,
    validate raises AdmissionError to reject.
    """

    def __init__(self, kind: str, path: str = "",
                 mutate: Optional[Callable] = None,
                 validate: Optional[Callable] = None,
                 operations: tuple = ("CREATE",)):
        self.kind = kind
        self.path = path
        self.mutate = mutate
        self.validate = validate
        self.operations = operations


class Watch:
    def __init__(self, kind: str, on_add=None, on_update=None, on_delete=None,
                 filter_fn: Optional[Callable] = None,
                 on_bulk_update: Optional[Callable] = None):
        self.kind = kind
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self.filter_fn = filter_fn
        # optional batched delivery: on_bulk_update([(old, new), ...]) for
        # patch_batch bursts (a 50k-bind flush otherwise pays per-event
        # handler dispatch + locking); watchers without it get per-pair
        # on_update calls
        self.on_bulk_update = on_bulk_update

    def _passes(self, o) -> bool:
        return self.filter_fn is None or self.filter_fn(o)


def _derive_pod(o) -> None:
    # compute the pod's aggregate resource request once at admission (the
    # apiserver computes derived defaults the same way): the memo rides
    # every clone handed out afterwards — watch ingest copies, bind patch
    # copies, echo copies — so TaskInfo rebuilds never re-parse quantities
    o.resource_request()


# kind -> derived-field computation run once when an object enters the store
_DERIVED = {"pods": _derive_pod}


class ObjectStore:
    """Thread-safe typed object store with admission + watch."""

    JOURNAL_CAPACITY = 65536
    EVENTS_CAPACITY = 16384

    def __init__(self, clock: Clock = GLOBAL_CLOCK):
        self._objects: Dict[str, Dict[str, object]] = {k: {} for k in KINDS}
        self._watches: Dict[str, List[Watch]] = defaultdict(list)
        self._hooks: List[AdmissionHook] = []
        self._rv = 0
        self._lock = threading.RLock()
        self.clock = clock
        from collections import deque as _deque
        # (kind, key, type, reason, message) records; bounded like the
        # reference's TTL'd core/v1 Events — unbounded growth was the one
        # leak a 100-cycle churn soak surfaced
        self.events = _deque(maxlen=self.EVENTS_CAPACITY)
        # change journal for remote watchers (the watch-stream seam of the
        # multi-process deployment, docs/deployment.md): (rv, action, kind,
        # object ref — safe to hold, internals are replaced never mutated)
        self._journal = _deque(maxlen=self.JOURNAL_CAPACITY)
        self._journal_cond = threading.Condition(self._lock)

    # -- keys --------------------------------------------------------------

    @staticmethod
    def key_of(kind: str, o) -> str:
        meta = o.metadata
        return meta.name if kind in CLUSTER_SCOPED else f"{meta.namespace}/{meta.name}"

    # -- admission ---------------------------------------------------------

    def register_admission(self, hook: AdmissionHook,
                           replace: bool = False) -> None:
        """replace=True drops existing hooks with the same (kind, path)
        first — a webhook-manager restart re-registers its services and
        must not leave stale duplicates calling dead endpoints."""
        if replace:
            self._hooks = [h for h in self._hooks
                           if not (h.kind == hook.kind
                                   and getattr(h, "path", "") == hook.path)]
        self._hooks.append(hook)

    def _admit(self, kind: str, operation: str, new_obj, old_obj=None) -> None:
        for h in self._hooks:
            if h.kind != kind or operation not in h.operations:
                continue
            if h.mutate is not None:
                h.mutate(operation, new_obj, old_obj)
        for h in self._hooks:
            if h.kind != kind or operation not in h.operations:
                continue
            if h.validate is not None:
                h.validate(operation, new_obj, old_obj)  # raises AdmissionError

    # -- CRUD --------------------------------------------------------------

    def create(self, kind: str, o, skip_admission: bool = False):
        # admission runs outside the store lock: remote admission hooks
        # (webhook-manager callbacks) must not stall every other writer
        if not skip_admission:
            self._admit(kind, "CREATE", o)
        derive = _DERIVED.get(kind)
        if derive is not None:
            derive(o)   # after admission: mutating hooks may change the spec
        with self._lock:
            key = self.key_of(kind, o)
            if key in self._objects[kind]:
                raise KeyError(f"{kind} {key!r} already exists")
            if not o.metadata.uid:
                o.metadata.uid = obj.new_uid(kind[:-1] if kind.endswith("s") else kind)
            if not o.metadata.creation_timestamp:
                o.metadata.creation_timestamp = self.clock.now()
            self._rv += 1
            o.metadata.resource_version = self._rv
            self._objects[kind][key] = o
            self._journal.append((self._rv, "ADDED", kind, o))
            self._journal_cond.notify_all()
            watches = list(self._watches[kind])
        for w in watches:
            if w.on_add and w._passes(o):
                # per-watcher copies: delivered objects are the watcher's
                # informer cache to mutate; the store's internal state (and
                # other watchers' views) must never alias them — the
                # scheduler writes task.pod.spec.node_name on its copy
                # exactly like the reference mutates informer pods
                w.on_add(fast_clone(o))
        return o

    # API-server semantics: reads hand out copies so callers can never mutate
    # stored state in place — a get+mutate+update round trip must present the
    # true old/new pair to watchers (the aliasing alternative silently breaks
    # phase-transition detection in controllers).

    def update(self, kind: str, o, skip_admission: bool = False):
        key = self.key_of(kind, o)
        if not skip_admission:
            with self._lock:
                old_pre = self._objects[kind].get(key)
            if old_pre is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._admit(kind, "UPDATE", o, old_pre)   # outside the lock
        derive = _DERIVED.get(kind)
        if derive is not None:
            derive(o)
        with self._lock:
            old = self._objects[kind].get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            if o.metadata.resource_version and \
                    o.metadata.resource_version != old.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key!r}: stale resource_version "
                    f"{o.metadata.resource_version} != {old.metadata.resource_version}")
            self._rv += 1
            o.metadata.resource_version = self._rv
            self._objects[kind][key] = o
            self._journal.append((self._rv, "MODIFIED", kind, o))
            self._journal_cond.notify_all()
            watches = list(self._watches[kind])
        for w in watches:
            old_p, new_p = w._passes(old), w._passes(o)
            # `old` left the store at replacement time, so it is exclusive
            # here; handlers receive it read-only and do not retain it —
            # only the live object needs per-watcher copies
            if old_p and new_p and w.on_update:
                w.on_update(old, fast_clone(o))
            elif not old_p and new_p and w.on_add:
                w.on_add(fast_clone(o))
            elif old_p and not new_p and w.on_delete:
                w.on_delete(old)
        return o

    def patch_batch(self, kind: str, patches, clone_fn=None) -> tuple:
        """Apply ``[(name, namespace, fn)]`` under ONE lock pass: each fn
        mutates a fresh clone of the stored object, which becomes the new
        stored version (rv bump + journal entry each). ``clone_fn``
        overrides the clone used to derive the new version (the bind path
        passes a shell-only pod cloner). Admission is skipped
        by design — the only caller is the bind path, and the reference's
        POST .../binding does not re-run pod admission either.

        Returns ``(pairs, missing)`` where pairs is [(old, new)] of applied
        patches and missing the [(name, namespace)] whose object was gone.

        Watch delivery: watchers exposing ``on_bulk_update`` get one call
        with their [(old, new)] list, where ``new`` is the STORE'S OWN
        object — the handler must never MUTATE it, but retaining it is
        allowed (stored objects are immutable in place: every update
        replaces them wholesale, a contract any future optimization here
        must preserve); this saves one deep pod copy per patch on the
        50k-bind flush. Watchers without a bulk handler get per-pair
        on_update with the usual per-watcher copy."""
        pairs: list = []
        missing: list = []
        watches: list = []
        try:
            with self._lock:
                try:
                    for name, namespace, fn in patches:
                        key = name if kind in CLUSTER_SCOPED \
                            else f"{namespace}/{name}"
                        old = self._objects[kind].get(key)
                        if old is None:
                            missing.append((name, namespace))
                            continue
                        new = (clone_fn or fast_clone)(old)
                        fn(new)   # a raising fn aborts THIS item pre-commit;
                        #           already-committed items still notify and
                        #           deliver below (finally) before re-raise
                        self._rv += 1
                        new.metadata.resource_version = self._rv
                        self._objects[kind][key] = new
                        self._journal.append((self._rv, "MODIFIED", kind, new))
                        pairs.append((old, new))
                finally:
                    if pairs:
                        self._journal_cond.notify_all()
                        watches = list(self._watches[kind])
        finally:
            for w in watches:
                if w.on_bulk_update is not None:
                    delivery = []
                    for old, new in pairs:
                        old_p, new_p = w._passes(old), w._passes(new)
                        if old_p and new_p:
                            delivery.append((old, new))
                        elif not old_p and new_p and w.on_add:
                            w.on_add(fast_clone(new))
                        elif old_p and not new_p and w.on_delete:
                            w.on_delete(old)
                    if delivery:
                        w.on_bulk_update(delivery)
                    continue
                for old, new in pairs:
                    old_p, new_p = w._passes(old), w._passes(new)
                    if old_p and new_p and w.on_update:
                        w.on_update(old, fast_clone(new))
                    elif not old_p and new_p and w.on_add:
                        w.on_add(fast_clone(new))
                    elif old_p and not new_p and w.on_delete:
                        w.on_delete(old)
        return pairs, missing

    def delete(self, kind: str, name: str, namespace: str = "default",
               skip_admission: bool = False) -> int:
        """Returns the deletion's resource version (remote mirrors dedup
        journal replays against it)."""
        key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
        if not skip_admission:
            with self._lock:
                old_pre = self._objects[kind].get(key)
            if old_pre is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._admit(kind, "DELETE", None, old_pre)   # outside the lock
        with self._lock:
            old = self._objects[kind].get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._rv += 1
            deleted_rv = self._rv
            self._journal.append((self._rv, "DELETED", kind, old))
            self._journal_cond.notify_all()
            del self._objects[kind][key]
            watches = list(self._watches[kind])
        for w in watches:
            if w.on_delete and w._passes(old):
                w.on_delete(old)   # removed from the store: exclusive now
        return deleted_rv

    def get(self, kind: str, name: str, namespace: str = "default"):
        key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
        with self._lock:
            o = self._objects[kind].get(key)
        return fast_clone(o) if o is not None else None

    def list(self, kind: str, namespace: Optional[str] = None) -> list:
        with self._lock:
            items = list(self._objects[kind].values())
        if namespace is not None and kind in NAMESPACED:
            items = [o for o in items if o.metadata.namespace == namespace]
        return [fast_clone(o) for o in items]

    def list_refs(self, kind: str, namespace: Optional[str] = None) -> list:
        """Live object references — no clone. Stored objects are replaced,
        never mutated in place (the same property the journal relies on),
        so each ref is a consistent view; callers MUST NOT mutate. This is
        the read-only audit path: the churn simulator's invariant checker
        walks every pod after every tick, and cloning 50k pods per audit
        would cost more than the scheduling cycle it checks."""
        with self._lock:
            items = list(self._objects[kind].values())
        if namespace is not None and kind in NAMESPACED:
            items = [o for o in items if o.metadata.namespace == namespace]
        return items

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, on_add=None, on_update=None, on_delete=None,
              filter_fn=None, sync: bool = True,
              on_bulk_update=None) -> Watch:
        """Subscribe to events for a kind; with sync=True, existing objects
        are replayed through on_add first (informer list+watch semantics)."""
        w = Watch(kind, on_add, on_update, on_delete, filter_fn,
                  on_bulk_update=on_bulk_update)
        with self._lock:
            self._watches[kind].append(w)
            existing = list(self._objects[kind].values()) if sync else []
        for o in existing:
            if w.on_add and w._passes(o):
                w.on_add(fast_clone(o))
        return w

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def events_since(self, rv: int, timeout: float = 25.0):
        """Long-poll the change journal: block until an event with
        resource_version > rv exists (or timeout), then return
        (events, current_rv, resync) where events is [(rv, action, kind,
        object)] and resync=True means rv predates the journal window —
        the caller must re-list everything and restart from current_rv."""
        import itertools
        with self._journal_cond:
            if not self._journal_cond.wait_for(
                    lambda: self._rv > rv, timeout=timeout):
                return [], self._rv, False
            if not self._journal or self._journal[0][0] > rv + 1:
                # gap: the journal cannot prove coverage of rv+1 (rolled
                # past it, or cleared by a snapshot restore) — the caller
                # must re-list
                return [], self._rv, True
            # journal rvs are contiguous (every rv bump appends exactly one
            # entry), so the slice start is an O(1) offset, not a scan
            start = max(0, rv + 1 - self._journal[0][0]) if self._journal \
                else 0
            events = list(itertools.islice(self._journal, start, None))
            return events, self._rv, False

    def unwatch(self, w: Watch) -> None:
        with self._lock:
            if w in self._watches[w.kind]:
                self._watches[w.kind].remove(w)

    # -- events (Recorder equivalent) --------------------------------------

    def record_event(self, kind: str, o, event_type: str, reason: str, message: str) -> None:
        self.events.append((kind, self.key_of(kind, o) if o is not None else "",
                            event_type, reason, message))

    # get already returns a deep copy; kept for callers written against the
    # earlier live-reference API
    get_copy = get
