"""In-process object store + watch bus.

The reference's distributed backbone is the Kubernetes API server: informer
watch streams in, binding/eviction/status writes out (SURVEY.md section 5.8).
In this standalone framework the same role is played by this store: typed
object collections with resource versions, admission hook chains (the webhook
manager registers here), and synchronous watch fan-out to informers (cache,
controllers).

Kinds and scoping mirror the reference's CRD groups plus the consumed core
slice; namespaced kinds key by "namespace/name", cluster-scoped by "name".
"""

from __future__ import annotations

import os
import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional

from ..models import objects as obj
from ..utils.clock import GLOBAL_CLOCK, Clock
from ..utils.fastclone import fast_clone

# shared worker pool for the sharded bulk-patch clone phase (phase 2 of
# the two-phase commit in ObjectStore._bulk_patch). Module-level so every
# store (tests build hundreds) shares a handful of threads; the pool only
# ever runs pure clone+patch closures over immutable inputs, so sharing
# is safe. Pool SIZE never affects results — shard content and publish
# order are fixed before any worker runs.
_FLUSH_POOL = None
_FLUSH_POOL_LOCK = threading.Lock()


def _flush_pool():
    global _FLUSH_POOL
    if _FLUSH_POOL is None:
        with _FLUSH_POOL_LOCK:
            if _FLUSH_POOL is None:
                from concurrent.futures import ThreadPoolExecutor
                try:
                    workers = int(os.environ.get(
                        "VOLCANO_FLUSH_WORKERS", "0")) or 0
                except ValueError:
                    workers = 0
                if workers <= 0:
                    workers = min(4, os.cpu_count() or 1)
                _FLUSH_POOL = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="flush-shard")
    return _FLUSH_POOL


# dedicated single-thread delivery executor: stage 3 of the flush
# pipeline (docs/design/bind_pipeline.md). ONE worker so deliveries
# retain shard order; shared module-wide like the clone pool (delivery
# order only matters within one store's patch, and a patch drains its
# own deliveries before returning).
_ECHO_POOL = None


def _echo_pool():
    global _ECHO_POOL
    if _ECHO_POOL is None:
        with _FLUSH_POOL_LOCK:
            if _ECHO_POOL is None:
                from concurrent.futures import ThreadPoolExecutor
                _ECHO_POOL = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="flush-echo")
    return _ECHO_POOL


# per-delivery context for watch handlers: when the echo worker runs a
# shard's delivery, ``origin`` carries the thread ident of the flush
# that produced it (the cache's expected-bind-echo hint is scoped to
# the writer's thread — the pipeline delivers on the writer's BEHALF)
# and ``commit_t`` the store-clock instant the shard published (the
# ledger's store_committed stamp, so committed->echo shows the echo
# pipeline's internal queue wait). ``depth`` flags handlers already
# running ON the echo worker, so a nested bulk patch inside a delivery
# degrades to inline delivery instead of deadlocking on the one worker.
_DELIVERY_CTX = threading.local()


def delivery_origin():
    """Thread ident of the flush a running watch delivery belongs to
    (the current thread outside the echo pipeline)."""
    return getattr(_DELIVERY_CTX, "origin", None) or threading.get_ident()


def delivery_commit_time():
    """Store-clock instant the delivering shard published, or None
    outside the echo pipeline."""
    return getattr(_DELIVERY_CTX, "commit_t", None)


# native publish (fastmodel.publish_shard): resolved lazily; the import
# is shared with the bind-clone fast path
_PUBLISH_NATIVE = [None, False]   # [module, probed]


def _publish_native():
    if not _PUBLISH_NATIVE[1]:
        _PUBLISH_NATIVE[1] = True
        try:
            from ..native.build import fastmodel
            fm = fastmodel()
            if fm is not None and hasattr(fm, "publish_shard"):
                _PUBLISH_NATIVE[0] = fm
        except Exception:
            _PUBLISH_NATIVE[0] = None
    return _PUBLISH_NATIVE[0]

def trace_in_ranges(ranges: list, rv: int):
    """Resolve ``rv`` against a ``trace_ranges()`` snapshot: ranges are
    non-overlapping and ascending by ``lo``, so a bisect finds the only
    candidate in O(log n) — the /watch handler resolves one rv per
    journal event against a single snapshot instead of re-copying the
    map per event."""
    import bisect
    i = bisect.bisect_right(ranges, rv, key=lambda r: r[0]) - 1
    if i >= 0 and ranges[i][1] >= rv:
        return ranges[i][2]
    return None


NAMESPACED = {"pods", "podgroups", "jobs", "commands", "resourcequotas", "services",
              "configmaps", "secrets", "networkpolicies", "persistentvolumeclaims"}
CLUSTER_SCOPED = {"nodes", "queues", "priorityclasses", "numatopologies",
                  "persistentvolumes"}
KINDS = NAMESPACED | CLUSTER_SCOPED


class AdmissionError(Exception):
    """Raised when a validating admission hook rejects an operation."""


class ConflictError(Exception):
    """Raised on update when the caller's copy is stale (optimistic
    concurrency, the apiserver 409). Re-get and retry."""


class FencedError(Exception):
    """Raised when a write carries a fencing token below the store's
    floor (docs/design/failover.md): the writer's lease incarnation has
    been superseded — a deposed leader with binds still in flight must
    NOT be able to land them after the standby took over. Unlike
    ConflictError this is not retryable by re-reading: the writer must
    stop writing until it re-acquires leadership (and a fresh token)."""


class ReadOnlyError(Exception):
    """The store is in durability-degraded read-only mode (the WAL hit
    ENOSPC/EIO, docs/design/durability.md): every mutation is refused
    before any state changes. The HTTP edge maps this to a structured
    503 + Retry-After, which the client pacer already honors."""

    def __init__(self, reason: str, retry_after: float = 5.0):
        super().__init__(f"store is read-only: {reason}")
        self.reason = reason
        self.retry_after = retry_after


class ReplicationGapError(Exception):
    """Raised by :meth:`ObjectStore.apply_replicated` when a replicated
    frame does not extend the follower mirror's journal contiguously
    (docs/design/federation.md). Carries ``expected``/``got`` rvs so the
    follower client can run a structured catch-up (re-fetch the missing
    range, or snapshot-bootstrap when the leader no longer retains it)
    instead of guessing."""

    def __init__(self, expected: int, got: int):
        super().__init__(
            f"replication gap: expected rv {expected}, got {got}")
        self.expected = expected
        self.got = got


class AdmissionHook:
    """One admission service (reference: pkg/webhooks/router/interface.go:38-48).

    ``mutate``/``validate`` receive (operation, new_obj, old_obj) where
    operation is "CREATE"|"UPDATE"|"DELETE"; mutate edits new_obj in place,
    validate raises AdmissionError to reject.
    """

    def __init__(self, kind: str, path: str = "",
                 mutate: Optional[Callable] = None,
                 validate: Optional[Callable] = None,
                 operations: tuple = ("CREATE",)):
        self.kind = kind
        self.path = path
        self.mutate = mutate
        self.validate = validate
        self.operations = operations


class Watch:
    def __init__(self, kind: str, on_add=None, on_update=None, on_delete=None,
                 filter_fn: Optional[Callable] = None,
                 on_bulk_update: Optional[Callable] = None,
                 filter_attr: Optional[tuple] = None):
        self.kind = kind
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self.filter_fn = filter_fn
        # optional batched delivery: on_bulk_update([(old, new), ...]) for
        # patch_batch bursts (a 50k-bind flush otherwise pays per-event
        # handler dispatch + locking); watchers without it get per-pair
        # on_update calls
        self.on_bulk_update = on_bulk_update
        # optional declaration that filter_fn is EQUIVALENT to the
        # attribute equality obj.<a0>.<a1> == expected —
        # ((a0, a1), expected) — letting bulk deliveries classify a
        # whole burst natively (two Python filter calls per pod on the
        # 50k flush otherwise). filter_fn stays authoritative: any
        # unexpected shape falls back to it.
        self.filter_attr = filter_attr

    def _passes(self, o) -> bool:
        return self.filter_fn is None or self.filter_fn(o)


def _derive_pod(o) -> None:
    # compute the pod's aggregate resource request once at admission (the
    # apiserver computes derived defaults the same way): the memo rides
    # every clone handed out afterwards — watch ingest copies, bind patch
    # copies, echo copies — so TaskInfo rebuilds never re-parse quantities
    o.resource_request()


# kind -> derived-field computation run once when an object enters the store
_DERIVED = {"pods": _derive_pod}


class ObjectStore:
    """Thread-safe typed object store with admission + watch."""

    JOURNAL_CAPACITY = 65536
    EVENTS_CAPACITY = 16384

    # sharded bulk-patch tuning (class attrs so tests can tune per store):
    # bursts at or below SHARD_SERIAL_MAX commit under one lock pass (the
    # classic serial path, exact legacy semantics); larger bursts split
    # into ceil(n / SHARD_TARGET) shards capped at SHARD_MAX. Shard count
    # is a pure function of the burst size — never of cpu count or pool
    # state — so double runs stay bit-identical (the sim determinism
    # contract, docs/design/bind_pipeline.md).
    SHARD_SERIAL_MAX = 512
    SHARD_TARGET = 2048
    SHARD_MAX = 8
    # native publish (fastmodel.publish_shard) switch — class attr so
    # the native-vs-Python parity tests can force either engine
    NATIVE_PUBLISH = True

    def __init__(self, clock: Clock = GLOBAL_CLOCK):
        self._objects: Dict[str, Dict[str, object]] = {k: {} for k in KINDS}
        self._watches: Dict[str, List[Watch]] = defaultdict(list)
        self._hooks: List[AdmissionHook] = []
        self._rv = 0
        self._lock = threading.RLock()
        self.clock = clock
        from collections import deque as _deque
        # (kind, key, type, reason, message) records; bounded like the
        # reference's TTL'd core/v1 Events — unbounded growth was the one
        # leak a 100-cycle churn soak surfaced
        self.events = _deque(maxlen=self.EVENTS_CAPACITY)
        # change journal for remote watchers (the watch-stream seam of the
        # multi-process deployment, docs/deployment.md): (rv, action, kind,
        # object ref — safe to hold, internals are replaced never mutated)
        self._journal = _deque(maxlen=self.JOURNAL_CAPACITY)
        self._journal_cond = threading.Condition(self._lock)
        # journal sequencer: _rv is the ALLOCATION counter (bulk patches
        # reserve whole contiguous ranges up front); _journal_tail is the
        # highest rv whose journal entry has been appended. The journal
        # stays rv-sorted and gap-free: an entry whose rv is ahead of the
        # tail (a single write that interleaved with an outstanding
        # reservation) parks in _journal_parked until the range below it
        # publishes. Readers (events_since, current_rv) see the tail.
        self._journal_tail = 0
        self._journal_parked: Dict[int, tuple] = {}
        # keys with a reserved-but-unpublished patch in flight, per kind;
        # update/delete on such a key waits on _flush_cond until its shard
        # publishes (a write racing the reservation window would otherwise
        # be silently overwritten by the shard's stale clone)
        self._inflight: Dict[str, set] = defaultdict(set)
        self._flush_cond = threading.Condition(self._lock)
        # lease fencing (docs/design/failover.md): the highest fencing
        # token this store has been told about (LeaderElector bumps it on
        # every lease acquisition). Writes stamped with a LOWER token are
        # rejected with FencedError; unstamped writes (fence=None — every
        # non-leader-scoped writer: controllers, tests, admission) pass
        # unchecked. Not persisted by snapshots: the floor re-derives
        # from the lease object on the next acquisition (the token itself
        # lives in the lease ConfigMap and IS snapshotted).
        self._fence_floor = 0
        self.fenced_writes = 0
        # durable write-ahead journal (docs/design/durability.md):
        # attached via attach_wal; every journal-tail advance forwards
        # its landed entries (O(1) ref enqueue). A WAL append failure
        # (ENOSPC/EIO) flips the store read-only — writes raise
        # ReadOnlyError before any state mutates.
        self.wal = None
        self._read_only_reason: Optional[str] = None
        # trace-context propagation (docs/design/observability.md): every
        # write form accepts a ``trace=`` correlation ID; committed rvs
        # are recorded here as (lo, hi, trace) ranges so a journal entry
        # (or a watch delivery carrying its rv) joins back to the write
        # that produced it via trace_of(rv). A side map, NOT a journal
        # tuple field: journal consumers keep their 4-tuple shape, and a
        # 50k-bind flush records ONE range instead of 50k entries.
        # Bounded like the journal; snapshot restores clear it (the
        # journal is cleared too — same lifetime).
        self._trace_ranges = _deque(maxlen=4096)

    # -- trace correlation -------------------------------------------------

    def _record_trace_locked(self, lo: int, hi: int, trace) -> None:
        if trace is not None and hi >= lo:
            self._trace_ranges.append((lo, hi, str(trace)))

    def trace_ranges(self) -> list:
        """Snapshot of the recorded (lo, hi, trace) ranges, ascending by
        rv (appends follow rv allocation order) — one lock pass for bulk
        consumers like the /watch handler; join single rvs with
        :func:`trace_in_ranges`."""
        with self._lock:
            return list(self._trace_ranges)

    def trace_of(self, rv: int):
        """Correlation ID of the write that produced ``rv`` (None when
        the write was unstamped or the record aged out)."""
        return trace_in_ranges(self.trace_ranges(), rv)

    # -- lease fencing -----------------------------------------------------

    def advance_fence(self, token: int) -> int:
        """Raise the write-fence floor to ``token`` (monotonic — a late
        call with an older token is a no-op). Returns the floor."""
        with self._lock:
            if token > self._fence_floor:
                self._fence_floor = token
                if self.wal is not None:
                    # fence advances are WAL records so recovery
                    # re-anchors the floor (docs/design/durability.md)
                    self.wal.append_fence(token)
            return self._fence_floor

    def fence_floor(self) -> int:
        with self._lock:
            return self._fence_floor

    def _check_fence_locked(self, fence: Optional[int]) -> None:
        """Reject a write stamped with a superseded fencing token.
        Caller holds ``self._lock``; raised before any state mutates."""
        if fence is not None and fence < self._fence_floor:
            self.fenced_writes += 1
            try:
                from ..metrics import metrics as _m
                _m.inc(_m.FENCED_WRITES)
            except Exception:
                pass
            raise FencedError(
                f"write fenced: token {fence} is behind the floor "
                f"{self._fence_floor} (lease superseded)")

    # -- durability (docs/design/durability.md) ----------------------------

    def attach_wal(self, wal) -> None:
        """Bind a :class:`~volcano_tpu.apiserver.wal.WriteAheadLog`:
        every journal-tail advance from here on forwards its landed
        entries. Attach AFTER recovery — the WAL must open its active
        segment at the recovered tail, not mid-replay."""
        with self._lock:
            self.wal = wal

    def enter_read_only(self, reason: str) -> None:
        """Durability degradation: refuse every mutation until the WAL
        heals (ENOSPC freed) or the process restarts."""
        with self._lock:
            self._read_only_reason = reason

    def exit_read_only(self) -> None:
        with self._lock:
            self._read_only_reason = None

    def read_only_reason(self) -> Optional[str]:
        with self._lock:
            return self._read_only_reason

    def _check_writable_locked(self) -> None:
        """Raised before any state mutates — an acked write must never
        exist only in RAM while the log can no longer persist it."""
        if self._read_only_reason is not None:
            raise ReadOnlyError(self._read_only_reason)

    # -- keys --------------------------------------------------------------

    @staticmethod
    def key_of(kind: str, o) -> str:
        meta = o.metadata
        return meta.name if kind in CLUSTER_SCOPED else f"{meta.namespace}/{meta.name}"

    # -- journal sequencer (caller holds self._lock) -----------------------

    def _journal_append_locked(self, rv: int, action: str, kind: str,
                               o) -> None:
        """Append one journal entry keeping the journal rv-sorted and
        gap-free. Entries ahead of the contiguous tail (a writer that
        interleaved with an outstanding bulk reservation) park until the
        range below them publishes; watchers are only notified when the
        tail actually advances (parked entries are not yet visible)."""
        if rv == self._journal_tail + 1:
            landed = [(rv, action, kind, o)]
            self._journal.append(landed[0])
            self._journal_tail = rv
            parked = self._journal_parked
            while parked:
                nxt = parked.pop(self._journal_tail + 1, None)
                if nxt is None:
                    break
                self._journal.append(nxt)
                landed.append(nxt)
                self._journal_tail += 1
            self._journal_cond.notify_all()
            if self.wal is not None:
                self.wal.append_entries(landed)
        else:
            self._journal_parked[rv] = (rv, action, kind, o)

    def _journal_extend_locked(self, entries) -> None:
        """Bulk sequencer append for a CONTIGUOUS ascending run of
        entries — ONE call per published shard instead of one per entry
        (journal write batching, the phase-3 lever from
        docs/design/bind_pipeline.md). Semantics match replaying
        :meth:`_journal_append_locked` over the run: either the whole run
        lands (its head extends the tail; parked entries above it drain
        after) or the whole run parks (nothing below it has landed —
        contiguity means no interior entry could land either)."""
        if not entries:
            return
        if entries[0][0] == self._journal_tail + 1:
            self._journal.extend(entries)
            self._journal_tail = entries[-1][0]
            drained = None
            parked = self._journal_parked
            while parked:
                nxt = parked.pop(self._journal_tail + 1, None)
                if nxt is None:
                    break
                self._journal.append(nxt)
                if drained is None:
                    drained = []
                drained.append(nxt)
                self._journal_tail += 1
            self._journal_cond.notify_all()
            if self.wal is not None:
                # no copy on the hot path: the WAL holds the run by ref
                # (journal lists are never mutated after publish)
                self.wal.append_entries(
                    entries if drained is None
                    else list(entries) + drained)
        else:
            for e in entries:
                self._journal_parked[e[0]] = e

    def _wait_journal_settled_locked(self) -> None:
        """Block (releasing the lock) until every allocated rv has
        published to the journal (``_rv == _journal_tail``) — the
        commit-order determinism barrier (docs/design/federation.md).

        EVERY rv allocation waits here first, so rv order is a pure
        function of commit order: a write can no longer slot before or
        after an outstanding bulk reservation depending on thread
        timing (the PR 11 interleaving finding), which is the
        precondition for any cross-replica consumer keying on rv.
        A settled journal implies no reservation is outstanding and no
        key is inflight, so this subsumes both the old per-key write
        barrier and the same-kind reservation wait. The parking
        machinery in the sequencer stays as a defensive invariant, but
        with this barrier no entry should ever park."""
        if self._rv != self._journal_tail:
            self._flush_cond.wait_for(
                lambda: self._rv == self._journal_tail)

    # -- admission ---------------------------------------------------------

    def register_admission(self, hook: AdmissionHook,
                           replace: bool = False) -> None:
        """replace=True drops existing hooks with the same (kind, path)
        first — a webhook-manager restart re-registers its services and
        must not leave stale duplicates calling dead endpoints."""
        if replace:
            self._hooks = [h for h in self._hooks
                           if not (h.kind == hook.kind
                                   and getattr(h, "path", "") == hook.path)]
        self._hooks.append(hook)

    def _admit(self, kind: str, operation: str, new_obj, old_obj=None) -> None:
        for h in self._hooks:
            if h.kind != kind or operation not in h.operations:
                continue
            if h.mutate is not None:
                h.mutate(operation, new_obj, old_obj)
        for h in self._hooks:
            if h.kind != kind or operation not in h.operations:
                continue
            if h.validate is not None:
                h.validate(operation, new_obj, old_obj)  # raises AdmissionError

    # -- CRUD --------------------------------------------------------------

    def create(self, kind: str, o, skip_admission: bool = False,
               fence: Optional[int] = None, trace: Optional[str] = None):
        # admission runs outside the store lock: remote admission hooks
        # (webhook-manager callbacks) must not stall every other writer
        if not skip_admission:
            self._admit(kind, "CREATE", o)
        derive = _DERIVED.get(kind)
        if derive is not None:
            derive(o)   # after admission: mutating hooks may change the spec
        with self._lock:
            self._wait_journal_settled_locked()
            # fence AFTER the settle wait (which releases the lock): a
            # takeover can happen while this writer queues behind an
            # in-flight flush, and the stale write must not land then
            self._check_writable_locked()
            self._check_fence_locked(fence)
            key = self.key_of(kind, o)
            if key in self._objects[kind]:
                raise KeyError(f"{kind} {key!r} already exists")
            if not o.metadata.uid:
                o.metadata.uid = obj.new_uid(kind[:-1] if kind.endswith("s") else kind)
            if not o.metadata.creation_timestamp:
                o.metadata.creation_timestamp = self.clock.now()
            self._rv += 1
            o.metadata.resource_version = self._rv
            self._objects[kind][key] = o
            self._journal_append_locked(self._rv, "ADDED", kind, o)
            self._record_trace_locked(self._rv, self._rv, trace)
            watches = list(self._watches[kind])
        for w in watches:
            if w.on_add and w._passes(o):
                # per-watcher copies: delivered objects are the watcher's
                # informer cache to mutate; the store's internal state (and
                # other watchers' views) must never alias them — the
                # scheduler writes task.pod.spec.node_name on its copy
                # exactly like the reference mutates informer pods
                w.on_add(fast_clone(o))
        return o

    # API-server semantics: reads hand out copies so callers can never mutate
    # stored state in place — a get+mutate+update round trip must present the
    # true old/new pair to watchers (the aliasing alternative silently breaks
    # phase-transition detection in controllers).

    def update(self, kind: str, o, skip_admission: bool = False,
               fence: Optional[int] = None, trace: Optional[str] = None):
        key = self.key_of(kind, o)
        if not skip_admission:
            with self._lock:
                old_pre = self._objects[kind].get(key)
            if old_pre is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._admit(kind, "UPDATE", o, old_pre)   # outside the lock
        derive = _DERIVED.get(kind)
        if derive is not None:
            derive(o)
        with self._lock:
            self._wait_journal_settled_locked()
            # fence AFTER the barrier wait (which releases the lock): a
            # takeover can happen while this writer queues behind an
            # in-flight flush, and the stale write must not land then
            self._check_writable_locked()
            self._check_fence_locked(fence)
            old = self._objects[kind].get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            if o.metadata.resource_version and \
                    o.metadata.resource_version != old.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key!r}: stale resource_version "
                    f"{o.metadata.resource_version} != {old.metadata.resource_version}")
            self._rv += 1
            o.metadata.resource_version = self._rv
            self._objects[kind][key] = o
            self._journal_append_locked(self._rv, "MODIFIED", kind, o)
            self._record_trace_locked(self._rv, self._rv, trace)
            watches = list(self._watches[kind])
        for w in watches:
            old_p, new_p = w._passes(old), w._passes(o)
            # `old` left the store at replacement time, so it is exclusive
            # here; handlers receive it read-only and do not retain it —
            # only the live object needs per-watcher copies
            if old_p and new_p and w.on_update:
                w.on_update(old, fast_clone(o))
            elif not old_p and new_p and w.on_add:
                w.on_add(fast_clone(o))
            elif old_p and not new_p and w.on_delete:
                w.on_delete(old)
        return o

    def patch_batch(self, kind: str, patches, clone_fn=None,
                    fence: Optional[int] = None,
                    trace: Optional[str] = None) -> tuple:
        """Apply ``[(name, namespace, fn)]`` as one bulk commit: each fn
        mutates a fresh clone of the stored object, which becomes the new
        stored version (rv bump + journal entry each). ``clone_fn``
        overrides the clone used to derive the new version (the bind path
        passes a shell-only pod cloner). Admission is skipped by design —
        the callers are the bind/status-writeback paths, and the
        reference's POST .../binding does not re-run pod admission either.

        Bursts up to ``SHARD_SERIAL_MAX`` commit under one lock pass (the
        classic serial path: a raising fn aborts its own item and every
        later one, with the committed prefix still announced). Larger
        bursts take the sharded two-phase pipeline — see :meth:`_bulk_patch`
        for the shard/reserve/publish protocol and its (slightly different)
        failure semantics.

        Returns ``(pairs, missing)`` where pairs is [(old, new)] of applied
        patches and missing the [(name, namespace)] whose object was gone.

        Watch delivery semantics (both paths, and both the bulk and
        per-pair forms): ``_passes(old)``/``_passes(new)`` are evaluated
        once per pair, and a filter FLIP mid-burst is delivered as a
        lifecycle transition, not an update — pass→fail fires ``on_delete``
        with the old object, fail→pass fires ``on_add`` with a fresh copy
        of the new one; only pass→pass pairs reach ``on_update`` /
        ``on_bulk_update``. Watchers exposing ``on_bulk_update`` get one
        call per commit unit (the whole burst on the serial path, one call
        PER SHARD on the sharded path) with their [(old, new)] list, where
        ``new`` is the STORE'S OWN object — the handler must never MUTATE
        it, but retaining it is allowed (stored objects are immutable in
        place: every update replaces them wholesale, a contract any future
        optimization here must preserve); this saves one deep pod copy per
        patch on the 50k-bind flush. Watchers without a bulk handler get
        per-pair on_update with the usual per-watcher copy."""
        def apply_fn(new, fn):
            fn(new)

        return self._bulk_patch(kind, patches, clone_fn or fast_clone,
                                apply_fn, None, fence=fence, trace=trace)

    def bind_pods(self, bindings, fence: Optional[int] = None,
                  trace: Optional[str] = None) -> tuple:
        """The bind-flush fast path: ``[(name, namespace, hostname)]`` →
        pod.spec.node_name patches through the same bulk engine as
        :meth:`patch_batch`, with the per-item closure replaced by a plain
        hostname payload so large bursts can promote the whole
        clone+patch+rv step of a shard into ONE ``fastmodel.c``
        ``bind_clone_pods`` call. Returns ``(pairs, missing)``."""
        from ..models.objects import clone_pod_for_bind

        def apply_fn(new, hostname):
            new.spec.node_name = hostname
            new.resource_request()   # seed the parse cache: the stored
            #                          version and every watcher echo copy
            #                          share it (TaskInfo rebuilds skip the
            #                          quantity parse)

        batch_shard = None
        try:
            from ..native.build import fastmodel
            fm = fastmodel()
        except Exception:
            fm = None
        if fm is not None and hasattr(fm, "bind_clone_pods"):
            def batch_shard(shard, rv_base):
                return fm.bind_clone_pods([old for _, old, _ in shard],
                                          [h for _, _, h in shard],
                                          rv_base + 1)

        return self._bulk_patch("pods", bindings, clone_pod_for_bind,
                                apply_fn, batch_shard, fence=fence,
                                trace=trace)

    def _shard_count(self, n: int) -> int:
        return min(self.SHARD_MAX, -(-n // self.SHARD_TARGET))

    def _bulk_patch(self, kind: str, items, clone_fn, apply_fn,
                    batch_shard, fence: Optional[int] = None,
                    trace: Optional[str] = None) -> tuple:
        """Bulk-commit engine behind patch_batch/bind_pods.

        ``items`` is [(name, namespace, payload)]; each applied item
        becomes ``new = clone_fn(old); apply_fn(new, payload)`` with a
        fresh rv. Two commit strategies:

        * serial (n <= SHARD_SERIAL_MAX): resolve, clone, patch, install
          and journal under ONE lock pass, exactly the legacy path.
        * sharded two-phase (docs/design/bind_pipeline.md): a SHORT lock
          reserves a contiguous rv range, snapshots the old objects and
          splits them into K stable shards (contiguous ranges of the
          input burst — gang locality preserved, see the phase-1 comment);
          the clone+patch of each shard then runs
          LOCK-FREE on a small worker pool (``batch_shard(shard, rv_base)``
          may replace a whole shard's clone+patch+rv loop with one native
          call); finally shards PUBLISH strictly in shard order — install
          + journal append (rv order == publish order) + one bulk watch
          delivery per shard — so a watcher's echo ingest of shard i
          overlaps shard i+1's clone work. While a reservation is
          outstanding its keys are write-barriered: update/delete on them
          block until the owning shard publishes, and interleaved writes
          on OTHER keys park their journal entries until the reserved
          range below them lands (see _journal_append_locked).

        Failure semantics differ on the sharded path: rvs are already
        reserved when apply_fn runs, so a raising apply_fn cannot abort
        the remaining items the way the serial path does — the failed
        item commits a NO-OP version (clone of the old object, rv bumped,
        journal entry, delivered as an old→unchanged update) to keep the
        journal gap-free, every other item commits normally, and the
        first error re-raises after delivery. Patch fns are not expected
        to raise; this is containment, not API.

        Determinism contract: shard assignment (contiguous ranges),
        per-shard rv ranges (shard order == input order) and publish
        order are all pure functions of the input burst — pool size and
        thread timing never change any observable ordering."""
        pairs: list = []
        missing: list = []
        watches: list = []
        resolved: list = []
        shards = bases = None
        cluster = kind in CLUSTER_SCOPED
        try:
            with self._lock:
                # phase 1: resolve + (for big bursts) reserve. Settles
                # the journal first: a reservation may only be taken
                # against a fully-published sequencer, so every rv range
                # is a pure function of commit order (and two
                # overlapping reservations can't deadlock on each
                # other's keys).
                self._wait_journal_settled_locked()
                # after the wait: a takeover may have happened while this
                # writer queued behind another flush — check at the last
                # possible instant before anything is resolved/reserved
                self._check_writable_locked()
                self._check_fence_locked(fence)
                objs = self._objects[kind]
                seen: set = set()
                for name, namespace, payload in items:
                    key = name if cluster else f"{namespace}/{name}"
                    old = objs.get(key)
                    if old is None:
                        missing.append((name, namespace))
                    else:
                        seen.add(key)
                        resolved.append((key, old, payload))
                n = len(resolved)
                if n == 0:
                    return [], missing
                # a repeated key must see the FIRST patch's result as its
                # old version — only the serial path chains patches that
                # way (phase 2 clones every item from the phase-1
                # snapshot), so duplicates force serial. No real caller
                # repeats keys (one bind / one status push per object).
                if n <= self.SHARD_SERIAL_MAX or self._shard_count(n) < 2 \
                        or len(seen) != n:
                    # serial path: commit everything under this lock pass.
                    # A raising apply_fn aborts THIS item pre-commit and
                    # every later one; already-committed items still
                    # notify and deliver below (finally) before re-raise.
                    try:
                        for key, _, payload in resolved:
                            # re-read under the held lock: a repeated key
                            # chains off the previous patch's result
                            old = objs[key]
                            new = clone_fn(old)
                            apply_fn(new, payload)
                            self._rv += 1
                            new.metadata.resource_version = self._rv
                            objs[key] = new
                            self._journal_append_locked(
                                self._rv, "MODIFIED", kind, new)
                            pairs.append((old, new))
                    finally:
                        if pairs:
                            self._record_trace_locked(
                                pairs[0][1].metadata.resource_version,
                                pairs[-1][1].metadata.resource_version,
                                trace)
                            watches = list(self._watches[kind])
                    return pairs, missing
                # sharded: reserve rvs + split; keys barriered until their
                # shard publishes. Shards are CONTIGUOUS RANGES of the
                # input burst, not a key hash: the burst arrives in gang
                # order, and range splitting preserves it — the cache's
                # echo ingest coalesces consecutive same-job pods into one
                # status-index pass, which a hash split (each gang's pods
                # scattered over every shard) measurably destroys. Ranges
                # are just as stable a function of the input burst, and rv
                # assignment stays exactly the legacy serial order.
                k = self._shard_count(n)
                step = -(-n // k)
                shards = [resolved[i:i + step]
                          for i in range(0, n, step)]
                bases = []
                rv = self._rv
                for s in shards:
                    bases.append(rv)
                    rv += len(s)
                # the whole reserved range commits (failures install
                # no-op versions), so one range record covers the burst
                self._record_trace_locked(self._rv + 1, rv, trace)
                self._rv = rv
                infl = self._inflight[kind]
                for key, _, _ in resolved:
                    infl.add(key)
                watches = list(self._watches[kind])
        finally:
            if shards is None:
                self._deliver_patch_pairs(watches, pairs)
        try:
            from ..metrics import metrics as _m
            _m.observe(_m.STORE_PATCH_SHARDS, len(shards), kind=kind)
        except Exception:
            pass
        return self._publish_shards(kind, shards, bases, watches, clone_fn,
                                    apply_fn, batch_shard, missing)

    def _publish_shards(self, kind, shards, bases, watches, clone_fn,
                        apply_fn, batch_shard, missing) -> tuple:
        """Phases 2+3+4 of :meth:`_bulk_patch` — the three-stage pipeline
        (docs/design/bind_pipeline.md): shard clones run on the worker
        pool, this thread publishes (installs + journals) strictly in
        shard order, and each published shard's watch delivery is handed
        to the single-thread echo executor. Shard *i*'s echo apply,
        shard *i+1*'s publish and shard *i+2*'s clone are therefore all
        in flight at once; all deliveries drain before the patch
        returns, so callers keep the synchronous contract."""
        first_err: list = [None]

        def run_shard(shard, rv_base):
            if batch_shard is not None:
                try:
                    return batch_shard(shard, rv_base)
                except Exception:
                    pass   # fall through to the per-item loop
            news = []
            rv = rv_base
            for key, old, payload in shard:
                rv += 1
                try:
                    new = clone_fn(old)
                    apply_fn(new, payload)
                except BaseException as e:
                    if first_err[0] is None:
                        first_err[0] = e
                    new = clone_fn(old)   # no-op version keeps the
                    #                       reserved rv/journal gap-free
                new.metadata.resource_version = rv
                news.append(new)
            return news

        from ..trace import tracer
        origin = delivery_origin()   # transitive: a nested patch inside
        #                              a delivery keeps the root writer
        deliver_err: list = [None]

        def deliver_task(spairs, commit_t):
            # every published shard DELIVERS, even after an earlier
            # shard's handler raised: the publish loop runs ahead of the
            # deliveries, so skipping would leave committed state no
            # watcher ever saw (the first handler error still re-raises
            # after the drain). Save/restore the context rather than
            # clearing it — a nested inline delivery must hand the outer
            # frame its origin back.
            prev = (getattr(_DELIVERY_CTX, "origin", None),
                    getattr(_DELIVERY_CTX, "commit_t", None))
            _DELIVERY_CTX.origin = origin
            _DELIVERY_CTX.commit_t = commit_t
            _DELIVERY_CTX.depth = getattr(_DELIVERY_CTX, "depth", 0) + 1
            try:
                with tracer.async_span("store.patch.deliver",
                                       pairs=len(spairs)):
                    self._deliver_patch_pairs(watches, spairs)
            except BaseException as e:
                if deliver_err[0] is None:
                    deliver_err[0] = e
            finally:
                _DELIVERY_CTX.depth -= 1
                _DELIVERY_CTX.origin, _DELIVERY_CTX.commit_t = prev

        # a bulk patch issued FROM a watch delivery already runs on the
        # echo worker: submitting its deliveries to the same one-thread
        # pool would deadlock — deliver inline instead (no pipeline).
        # Inline deliveries are DEFERRED until every shard has
        # published: a handler inside one may write, and the settle
        # barrier (_wait_journal_settled_locked) would deadlock against
        # this thread's own still-unpublished shards otherwise.
        inline_echo = getattr(_DELIVERY_CTX, "depth", 0) > 0
        pairs_all: list = []
        published = 0
        deliveries: list = []
        inline_pending: list = []
        try:
            # everything from here until the last shard publishes sits
            # inside the recovery scope: a failure anywhere (pool
            # creation, submit, a worker, a watch handler) MUST still
            # land the reserved rvs and release the key barriers, or the
            # journal tail stalls and every later write blocks forever
            pool = _flush_pool()
            epool = None if inline_echo else _echo_pool()
            futures = [pool.submit(run_shard, s, b)
                       for s, b in zip(shards, bases)]
            for shard, base, fut in zip(shards, bases, futures):
                with tracer.async_span("store.patch.clone_wait"):
                    news = fut.result()
                with tracer.async_span("store.patch.publish"):
                    spairs = self._install_shard(kind, shard, news,
                                                        base)
                published += 1
                pairs_all.extend(spairs)
                commit_t = self.clock.now()
                if epool is None:
                    inline_pending.append((spairs, commit_t))
                else:
                    deliveries.append(
                        epool.submit(deliver_task, spairs, commit_t))
        finally:
            if published < len(shards):
                # fill the unpublished remainder with no-op versions
                for shard, base in list(zip(shards, bases))[published:]:
                    news = [clone_fn(old) for _, old, _ in shard]
                    for i, new in enumerate(news):
                        new.metadata.resource_version = base + i + 1
                    self._install_shard(kind, shard, news, base)
            # deferred inline deliveries run with the journal settled
            # (still shard order, still before the patch returns)
            for spairs, commit_t in inline_pending:
                deliver_task(spairs, commit_t)
            # echo drain: the patch must not return (nor the bind flush
            # release its barrier) with deliveries still in flight
            if deliveries:
                with tracer.async_span("store.patch.echo_wait"):
                    for f in deliveries:
                        f.result()
        if first_err[0] is not None:
            raise first_err[0]
        if deliver_err[0] is not None:
            raise deliver_err[0]
        return pairs_all, missing

    def _install_shard(self, kind, shard, news, rv_base) -> list:
        """Ordered-publish step (acquires the store lock itself — NOT a
        `*_locked` callee): install a shard's new versions, append
        their journal entries (the contiguous reserved rvs from
        ``rv_base + 1``) and release the shard's write barrier. The whole
        per-shard loop — install + journal-entry construction + delivery
        pair assembly — is ONE ``fastmodel.publish_shard`` call when the
        native module is available (the Python loop was a measured slice
        of the 50k-bind commit path); the journal batch then lands
        through ONE sequencer call. Returns the shard's [(old, new)]."""
        fm = _publish_native() if self.NATIVE_PUBLISH else None
        with self._lock:
            objs = self._objects[kind]
            infl = self._inflight[kind]
            if fm is not None:
                try:
                    entries, pairs = fm.publish_shard(objs, infl, kind,
                                                      shard, news, rv_base)
                    self._journal_extend_locked(entries)
                    self._flush_cond.notify_all()
                    return pairs
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "native publish_shard failed; Python fallback")
                    # fall through: the Python loop re-applies the
                    # install idempotently
            entries = []
            for (key, _, _), new in zip(shard, news):
                objs[key] = new
                infl.discard(key)
                entries.append((new.metadata.resource_version, "MODIFIED",
                                kind, new))
            # journal write batching: the shard's contiguous reserved rvs
            # land (or park) through ONE sequencer call
            self._journal_extend_locked(entries)
            self._flush_cond.notify_all()
        return [(old, new) for (_, old, _), new in zip(shard, news)]

    def _deliver_patch_pairs(self, watches, pairs) -> None:
        """Watch delivery for one commit unit (whole serial burst or one
        shard): _passes evaluated once per pair, filter flips delivered
        as add/delete lifecycle transitions (see patch_batch docstring)."""
        if not pairs:
            return
        for w in watches:
            bulk = w.on_bulk_update
            if bulk is not None and w.filter_fn is None:
                bulk(pairs)
                continue
            if bulk is not None:
                if w.filter_attr is not None:
                    # native classification for declared attribute-
                    # equality filters; filter_fn stays the authority
                    # on any failure. Flips come back as ordered
                    # (is_add, obj) events, fired exactly like the
                    # per-pair loop below would fire them.
                    fm = _publish_native() if self.NATIVE_PUBLISH else None
                    if fm is not None and hasattr(fm,
                                                  "attr_eq_filter_pairs"):
                        (path0, path1), expected = w.filter_attr
                        try:
                            delivery, flips = fm.attr_eq_filter_pairs(
                                pairs if isinstance(pairs, list)
                                else list(pairs),
                                path0, path1, expected)
                        except Exception:
                            pass
                        else:
                            for is_add, o in flips:
                                if is_add and w.on_add:
                                    w.on_add(fast_clone(o))
                                elif not is_add and w.on_delete:
                                    w.on_delete(o)
                            if delivery:
                                bulk(delivery)
                            continue
                fl = w.filter_fn   # direct: the _passes wrapper is two
                #                    extra calls per pod on a 50k burst
                delivery = []
                for old, new in pairs:
                    old_p, new_p = fl(old), fl(new)
                    if old_p and new_p:
                        delivery.append((old, new))
                    elif not old_p and new_p and w.on_add:
                        w.on_add(fast_clone(new))
                    elif old_p and not new_p and w.on_delete:
                        w.on_delete(old)
                if delivery:
                    bulk(delivery)
                continue
            for old, new in pairs:
                old_p, new_p = w._passes(old), w._passes(new)
                if old_p and new_p and w.on_update:
                    w.on_update(old, fast_clone(new))
                elif not old_p and new_p and w.on_add:
                    w.on_add(fast_clone(new))
                elif old_p and not new_p and w.on_delete:
                    w.on_delete(old)

    def delete(self, kind: str, name: str, namespace: str = "default",
               skip_admission: bool = False,
               fence: Optional[int] = None,
               trace: Optional[str] = None) -> int:
        """Returns the deletion's resource version (remote mirrors dedup
        journal replays against it)."""
        key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
        if not skip_admission:
            with self._lock:
                old_pre = self._objects[kind].get(key)
            if old_pre is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._admit(kind, "DELETE", None, old_pre)   # outside the lock
        with self._lock:
            self._wait_journal_settled_locked()
            # fence after the barrier wait — see update()
            self._check_writable_locked()
            self._check_fence_locked(fence)
            old = self._objects[kind].get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._rv += 1
            deleted_rv = self._rv
            self._journal_append_locked(self._rv, "DELETED", kind, old)
            self._record_trace_locked(self._rv, self._rv, trace)
            del self._objects[kind][key]
            watches = list(self._watches[kind])
        for w in watches:
            if w.on_delete and w._passes(old):
                w.on_delete(old)   # removed from the store: exclusive now
        return deleted_rv

    # -- replication: follower mirror install (docs/design/federation.md) ---

    def apply_replicated(self, entries, epoch: Optional[int] = None) -> int:
        """Install a contiguous run of replicated journal entries at the
        LEADER'S rvs — the follower mirror's install path. Unlike the
        RemoteStore informer mirror (which re-stamps mirror-local rvs),
        the follower keeps the leader's rv on every object, so the
        anti-entropy fingerprint over ``{key: (rv, obj)}`` views is
        bit-identical across replicas — the cross-replica divergence
        audit relies on it.

        ``entries`` is ``[(rv, action, kind, obj)]``, ascending and
        contiguous; the run must extend the mirror's journal tail
        exactly (``entries[0].rv == tail + 1``) or
        :class:`ReplicationGapError` carries ``(expected, got)`` for the
        follower's structured catch-up. ``epoch`` is the shipping
        leader's election epoch, checked against the fence floor like
        any fenced write — a deposed leader's frames raise FencedError
        before anything mutates. Local watchers see the usual
        add/update/delete lifecycle (filter flips included). Returns
        the new journal tail."""
        if not entries:
            return self.current_rv()
        deliveries: list = []
        with self._lock:
            self._wait_journal_settled_locked()
            self._check_writable_locked()
            self._check_fence_locked(epoch)
            rvs = [int(e[0]) for e in entries]
            expected = self._journal_tail + 1
            if rvs[0] != expected:
                raise ReplicationGapError(expected, rvs[0])
            for a, b in zip(rvs, rvs[1:]):
                if b != a + 1:
                    raise ReplicationGapError(a + 1, b)
            # derive BEFORE any mutation: a malformed object raising
            # mid-run would otherwise leave a partially-applied frame
            # (re-seeds the request memo the HTTP decode dropped)
            for _, action, kind, o in entries:
                derive = _DERIVED.get(kind)
                if derive is not None and action != "DELETED":
                    derive(o)
            journal: list = []
            for rv, (_, action, kind, o) in zip(rvs, entries):
                objs = self._objects[kind]
                key = self.key_of(kind, o)
                old = objs.get(key)
                if action == "DELETED":
                    objs.pop(key, None)
                else:
                    o.metadata.resource_version = rv
                    objs[key] = o
                journal.append((rv, action, kind, o))
                deliveries.append((action, kind, old, o))
            self._rv = rvs[-1]
            self._journal_extend_locked(journal)
            self._flush_cond.notify_all()
            watches = {k: list(self._watches[k])
                       for k in {d[1] for d in deliveries}}
        for action, kind, old, o in deliveries:
            for w in watches[kind]:
                self._deliver_replicated(w, action, old, o)
        return rvs[-1]

    @staticmethod
    def _deliver_replicated(w: Watch, action: str, old, o) -> None:
        """One replicated entry through one watch, with the same filter-
        flip lifecycle semantics as :meth:`update` (the journal only
        carries the new object; ``old`` is the mirror's prior version,
        None when the entry is the key's first appearance here)."""
        if action == "DELETED":
            if w.on_delete and w._passes(o):
                w.on_delete(o)
            return
        if old is None:
            if w.on_add and w._passes(o):
                w.on_add(fast_clone(o))
            return
        old_p, new_p = w._passes(old), w._passes(o)
        if old_p and new_p and w.on_update:
            w.on_update(old, fast_clone(o))
        elif not old_p and new_p and w.on_add:
            w.on_add(fast_clone(o))
        elif old_p and not new_p and w.on_delete:
            w.on_delete(old)

    def install_snapshot(self, objects: Dict[str, dict], rv: int,
                         epoch: Optional[int] = None) -> int:
        """Replace the mirror's entire object state with a leader
        snapshot anchored at ``rv`` — the cold-follower bootstrap, and
        the catch-up path when the leader no longer retains a gapped
        range. ``objects`` is ``{kind: {key: obj}}`` with every object
        already carrying its leader rv. The journal clears (history
        below the anchor is unknown here), so journal cursors below the
        new tail take the structured relist on their next dispatch —
        exactly the contract a snapshot restore already has. Local
        Watch handlers are NOT replayed: the mirror's consumers are
        journal cursors (the serving hub), which the relist re-anchors."""
        # validate + derive the ENTIRE snapshot before touching any
        # state: an interrupted or malformed transfer must leave the
        # mirror exactly as it was (all-or-nothing), never a mix of
        # new kinds over old ones
        staged: Dict[str, dict] = {}
        for kind in KINDS:
            incoming = objects.get(kind) or {}
            derive = _DERIVED.get(kind)
            if derive is not None:
                for o in incoming.values():
                    derive(o)
            staged[kind] = dict(incoming)
        with self._lock:
            self._wait_journal_settled_locked()
            self._check_writable_locked()
            self._check_fence_locked(epoch)
            for kind in KINDS:
                self._objects[kind] = staged[kind]
            self._journal.clear()
            self._journal_parked.clear()
            self._trace_ranges.clear()
            self._rv = self._journal_tail = int(rv)
            self._journal_cond.notify_all()
            self._flush_cond.notify_all()
            if self.wal is not None:
                # the rv space changed wholesale: the WAL drops its
                # pre-install pending batches and schedules a generation
                # cutover + fresh snapshot (flag-set only — the flusher
                # does the IO off this lock)
                self.wal.on_snapshot_installed(int(rv))
        return int(rv)

    def get(self, kind: str, name: str, namespace: str = "default"):
        key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
        with self._lock:
            o = self._objects[kind].get(key)
        return fast_clone(o) if o is not None else None

    def list(self, kind: str, namespace: Optional[str] = None) -> list:
        with self._lock:
            items = list(self._objects[kind].values())
        if namespace is not None and kind in NAMESPACED:
            items = [o for o in items if o.metadata.namespace == namespace]
        return [fast_clone(o) for o in items]

    def get_ref(self, kind: str, name: str, namespace: str = "default"):
        """Live object reference for one key — the single-key sibling of
        :meth:`list_refs` (no clone). Stored objects are replaced, never
        mutated in place, so the ref is a consistent view; callers MUST
        NOT mutate. This is the HTTP read path's no-copy serve
        (docs/design/serving.md): encoding a response reads the object,
        it never writes it, and the per-request deep copy was the read
        path's whole cost."""
        key = name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"
        with self._lock:
            return self._objects[kind].get(key)

    def list_refs(self, kind: str, namespace: Optional[str] = None) -> list:
        """Live object references — no clone. Stored objects are replaced,
        never mutated in place (the same property the journal relies on),
        so each ref is a consistent view; callers MUST NOT mutate. This is
        the read-only audit path: the churn simulator's invariant checker
        walks every pod after every tick, and cloning 50k pods per audit
        would cost more than the scheduling cycle it checks."""
        with self._lock:
            items = list(self._objects[kind].values())
        if namespace is not None and kind in NAMESPACED:
            items = [o for o in items if o.metadata.namespace == namespace]
        return items

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, on_add=None, on_update=None, on_delete=None,
              filter_fn=None, sync: bool = True,
              on_bulk_update=None, filter_attr=None) -> Watch:
        """Subscribe to events for a kind; with sync=True, existing objects
        are replayed through on_add first (informer list+watch semantics).
        ``filter_attr=((a0, a1), expected)`` optionally declares that
        ``filter_fn`` is equivalent to ``obj.<a0>.<a1> == expected`` so
        bulk deliveries can classify the burst natively."""
        w = Watch(kind, on_add, on_update, on_delete, filter_fn,
                  on_bulk_update=on_bulk_update, filter_attr=filter_attr)
        with self._lock:
            # wait out an in-flight sharded patch: its delivery list was
            # snapshotted at reservation time, so a watch registered
            # mid-flight would neither appear in that snapshot nor see
            # the unpublished shards in its sync replay — it would
            # silently miss part of the burst forever
            self._wait_journal_settled_locked()
            self._watches[kind].append(w)
            existing = list(self._objects[kind].values()) if sync else []
        for o in existing:
            if w.on_add and w._passes(o):
                w.on_add(fast_clone(o))
        return w

    def current_rv(self) -> int:
        """The watch-visible resource version: the journal's contiguous
        tail. During a bulk-patch reservation window this can trail the
        allocation counter ``_rv`` — cursors anchored here never skip the
        reserved-but-unpublished entries."""
        with self._lock:
            return self._journal_tail

    def journal_window(self) -> tuple:
        """``(head_rv, tail_rv)`` of the contiguous journal window: head
        is the first retained entry's rv (``tail + 1`` when the journal
        is empty), tail the watch-visible contiguous tail. A cursor c is
        servable iff ``c + 1 >= head`` — the serving hub's structured-
        relist decision (docs/design/serving.md)."""
        with self._lock:
            head = self._journal[0][0] if self._journal \
                else self._journal_tail + 1
            return head, self._journal_tail

    def events_since(self, rv: int, timeout: float = 25.0):
        """Long-poll the change journal: block until an event with
        resource_version > rv exists (or timeout), then return
        (events, current_rv, resync) where events is [(rv, action, kind,
        object)] and resync=True means rv predates the journal window —
        the caller must re-list everything and restart from current_rv.
        Visibility is bounded by the journal's contiguous tail (entries
        parked behind an in-flight bulk reservation are not yet events)."""
        import itertools
        with self._journal_cond:
            if not self._journal_cond.wait_for(
                    lambda: self._journal_tail > rv, timeout=timeout):
                return [], self._journal_tail, False
            if not self._journal or self._journal[0][0] > rv + 1:
                # gap: the journal cannot prove coverage of rv+1 (rolled
                # past it, or cleared by a snapshot restore) — the caller
                # must re-list
                return [], self._journal_tail, True
            # journal rvs are contiguous up to the tail (reserved ranges
            # publish in rv order; interleaved writers park until the
            # range below them lands), so the slice start is an O(1)
            # offset, not a scan
            start = max(0, rv + 1 - self._journal[0][0]) if self._journal \
                else 0
            events = list(itertools.islice(self._journal, start, None))
            return events, self._journal_tail, False

    def unwatch(self, w: Watch) -> None:
        with self._lock:
            if w in self._watches[w.kind]:
                self._watches[w.kind].remove(w)

    # -- events (Recorder equivalent) --------------------------------------

    def record_event(self, kind: str, o, event_type: str, reason: str, message: str) -> None:
        self.events.append((kind, self.key_of(kind, o) if o is not None else "",
                            event_type, reason, message))

    # get already returns a deep copy; kept for callers written against the
    # earlier live-reference API
    get_copy = get
