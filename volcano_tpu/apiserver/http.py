"""HTTP front-end for the object store + client.

The reference's CLI and controllers speak REST to the Kubernetes API server;
this module gives the standalone framework the same seam: a threaded HTTP
server over an :class:`ObjectStore` and a client exposing the store's CRUD
interface over the wire. Watches stay in-process (scheduler/controllers run
in the serving process; SURVEY.md section 5.8).

Routes (namespaced kinds):
    GET    /apis/{kind}?namespace=ns      list
    GET    /apis/{kind}/{ns}/{name}       get
    POST   /apis/{kind}                   create
    PUT    /apis/{kind}/{ns}/{name}       update
    DELETE /apis/{kind}/{ns}/{name}       delete
Cluster-scoped kinds use /apis/{kind}/{name}.
Admission rejections -> 422, conflicts -> 409, missing -> 404.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .codec import decode_object, encode_object
from .store import (CLUSTER_SCOPED, KINDS, AdmissionError, ConflictError,
                    FencedError, ObjectStore)


def _fence_of(query: dict):
    """Optional fencing token from a write request's query string
    (?fence=N). Fenced rejections map to HTTP 412 Precondition Failed —
    distinct from the 409 conflict, which is retryable by re-reading.
    Raises ValueError on a malformed token (handlers answer 400: a
    garbled fence must never silently degrade to an UNfenced write)."""
    raw = query.get("fence", [None])[0]
    return int(raw) if raw is not None else None


# correlation-ID wire format (docs/design/observability.md): writes carry
# ``?trace=<id>`` and journal deliveries echo it back as the event's
# ``trace`` field, so one bind stays traceable scheduler -> store journal
# -> remote mirror. IDs are opaque strings, length-capped so a hostile
# query string can't bloat the store's trace ranges.
TRACE_MAX_LEN = 128


def _trace_of(query: dict):
    raw = query.get("trace", [None])[0]
    return raw[:TRACE_MAX_LEN] if raw else None


class StoreHTTPServer:
    def __init__(self, store: ObjectStore, host: str = "127.0.0.1",
                 port: int = 8181):
        self.store = store
        handler = self._make_handler()
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        store = self.store

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _parse(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                query = urllib.parse.parse_qs(parsed.query)
                if len(parts) < 2 or parts[0] != "apis" or parts[1] not in KINDS:
                    return None
                kind = parts[1]
                rest = parts[2:]
                if kind in CLUSTER_SCOPED:
                    name = rest[0] if rest else None
                    ns = "default"
                else:
                    ns = rest[0] if len(rest) >= 2 else \
                        (query.get("namespace", ["default"])[0])
                    name = rest[1] if len(rest) >= 2 else None
                return kind, ns, name, query

            def _body(self):
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length)) if length else None

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/rv":
                    return self._send(200, {"rv": store.current_rv()})
                if parsed.path == "/fence":
                    return self._send(200, {"floor": store.fence_floor()})
                if parsed.path == "/watch":
                    q = urllib.parse.parse_qs(parsed.query)
                    since = int(q.get("since", ["0"])[0])
                    timeout = min(60.0, float(q.get("timeout", ["25"])[0]))
                    events, rv, resync = store.events_since(since, timeout)
                    # ONE trace-map snapshot for the whole response (a
                    # 50k-event long poll must not copy the map per
                    # event); each rv resolves by bisect
                    from .store import trace_in_ranges
                    ranges = store.trace_ranges() if events else []
                    payload = []
                    for erv, action, kind, o in events:
                        ev = {"rv": erv, "action": action, "kind": kind,
                              "object": encode_object(kind, o)}
                        trace = trace_in_ranges(ranges, erv)
                        if trace is not None:
                            ev["trace"] = trace
                        payload.append(ev)
                    return self._send(200, {"rv": rv, "resync": resync,
                                            "events": payload})
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, ns, name, query = route
                if name is None:
                    namespace = query.get("namespace", [None])[0]
                    items = store.list(kind, namespace)
                    return self._send(200, {"items": [
                        encode_object(kind, o) for o in items]})
                o = store.get(kind, name, ns)
                if o is None:
                    return self._send(404, {"error": f"{kind} {name} not found"})
                return self._send(200, encode_object(kind, o))

            def do_POST(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/fence":
                    # the LeaderElector of a remote process announcing its
                    # freshly-acquired token; floor advance is monotonic
                    body = self._body() or {}
                    floor = store.advance_fence(int(body.get("token", 0)))
                    return self._send(200, {"floor": floor})
                if parsed.path == "/events":
                    body = self._body()
                    o = decode_object(body["kind"], body["object"]) \
                        if body.get("object") else None
                    store.record_event(body["kind"], o, body["event_type"],
                                       body["reason"], body["message"])
                    return self._send(201, {"status": "recorded"})
                if parsed.path == "/admissionwebhooks":
                    # the webhook-manager's self-registration: the store
                    # calls back over HTTPS on matching operations,
                    # verifying the webhook's serving certificate against
                    # the registered CA bundle (the reference registers
                    # WebhookConfigurations carrying caBundle,
                    # cmd/webhook-manager/app/server.go:64-87 +
                    # util.go:37-130)
                    body = self._body()
                    from .remote import RemoteAdmissionHook
                    store.register_admission(RemoteAdmissionHook(
                        kind=body["kind"], path=body.get("path", ""),
                        url=body["url"],
                        operations=tuple(body.get("operations",
                                                  ("CREATE",))),
                        ca_bundle=body.get("ca_bundle", "")),
                        replace=True)
                    return self._send(201, {"status": "registered"})
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, _ns, _name, query = route
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    o = decode_object(kind, self._body())
                    created = store.create(kind, o, fence=fence,
                                           trace=_trace_of(query))
                    return self._send(201, encode_object(kind, created))
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(409, {"error": str(e)})

            def do_PUT(self):
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, _ns, _name, query = route
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    o = decode_object(kind, self._body())
                    updated = store.update(kind, o, fence=fence,
                                           trace=_trace_of(query))
                    return self._send(200, encode_object(kind, updated))
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except ConflictError as e:
                    return self._send(409, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(404, {"error": str(e)})

            def do_DELETE(self):
                route = self._parse()
                if route is None or route[2] is None:
                    return self._send(404, {"error": "not found"})
                kind, ns, name, query = route
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    rv = store.delete(kind, name, ns, fence=fence,
                                      trace=_trace_of(query))
                    return self._send(200, {"status": "deleted", "rv": rv})
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(404, {"error": str(e)})

        return Handler

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class StoreClient:
    """Remote client mirroring the ObjectStore CRUD surface."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    def _request(self, method: str, path: str, payload=None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read()).get("error", str(e))
            except Exception:
                message = str(e)
            raise ApiError(e.code, message) from None

    def _path(self, kind: str, name: Optional[str] = None,
              namespace: str = "default") -> str:
        if name is None:
            return f"/apis/{kind}"
        if kind in CLUSTER_SCOPED:
            return f"/apis/{kind}/{name}"
        return f"/apis/{kind}/{namespace}/{name}"

    def get(self, kind: str, name: str, namespace: str = "default"):
        try:
            data = self._request("GET", self._path(kind, name, namespace))
        except ApiError as e:
            if e.code == 404:
                return None
            raise
        return decode_object(kind, data)

    def list(self, kind: str, namespace: Optional[str] = None) -> list:
        path = self._path(kind)
        if namespace is not None:
            path += f"?namespace={urllib.parse.quote(namespace)}"
        data = self._request("GET", path)
        return [decode_object(kind, item) for item in data["items"]]

    @staticmethod
    def _with_params(path: str, fence, trace=None) -> str:
        params = []
        if fence is not None:
            params.append(f"fence={int(fence)}")
        if trace is not None:
            params.append(f"trace={urllib.parse.quote(str(trace))}")
        return f"{path}?{'&'.join(params)}" if params else path

    def create(self, kind: str, o, fence=None, trace=None):
        data = self._request("POST",
                             self._with_params(self._path(kind), fence,
                                               trace),
                             encode_object(kind, o))
        return decode_object(kind, data)

    def update(self, kind: str, o, fence=None, trace=None):
        path = self._path(kind, o.metadata.name, o.metadata.namespace)
        data = self._request("PUT", self._with_params(path, fence, trace),
                             encode_object(kind, o))
        return decode_object(kind, data)

    def delete(self, kind: str, name: str, namespace: str = "default",
               fence=None, trace=None):
        return self._request(
            "DELETE", self._with_params(self._path(kind, name, namespace),
                                        fence, trace))

    def advance_fence(self, token: int) -> int:
        return int(self._request("POST", "/fence",
                                 {"token": int(token)}).get("floor", 0))
