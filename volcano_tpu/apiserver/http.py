"""HTTP front-end for the object store + client.

The reference's CLI and controllers speak REST to the Kubernetes API server;
this module gives the standalone framework the same seam: a threaded HTTP
server over an :class:`ObjectStore` and a client exposing the store's CRUD
interface over the wire. Watches stay in-process (scheduler/controllers run
in the serving process; SURVEY.md section 5.8).

Routes (namespaced kinds):
    GET    /apis/{kind}?namespace=ns      list
    GET    /apis/{kind}/{ns}/{name}       get
    POST   /apis/{kind}                   create
    PUT    /apis/{kind}/{ns}/{name}       update
    DELETE /apis/{kind}/{ns}/{name}       delete
Cluster-scoped kinds use /apis/{kind}/{name}.
Admission rejections -> 422, conflicts -> 409, missing -> 404.

Serving-hub era (docs/design/serving.md): the server speaks HTTP/1.1
with keep-alive (every response carries Content-Length or chunked
framing — one TCP connection serves a client's whole write stream), an
optional :class:`~volcano_tpu.serving.hub.ServingHub` adds the chunked
``/watchstream?cursor=rv`` streaming endpoint (coalesced event-batch
frames pushed as they publish, heartbeat pings between), and an optional
:class:`~volcano_tpu.serving.admission.AdmissionController` enforces
per-tenant write rate limits at the edge — throttled writes answer a
structured 429 with Retry-After, which :class:`StoreClient` surfaces as
``ApiError.retry_after`` and RemoteStore honors in its backoff.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence, Union

from ..utils.backoff import seeded_backoff
from .codec import decode_object, encode_object
from .store import (CLUSTER_SCOPED, KINDS, AdmissionError, ConflictError,
                    FencedError, ObjectStore, ReadOnlyError)


def _fence_of(query: dict):
    """Optional fencing token from a write request's query string
    (?fence=N). Fenced rejections map to HTTP 412 Precondition Failed —
    distinct from the 409 conflict, which is retryable by re-reading.
    Raises ValueError on a malformed token (handlers answer 400: a
    garbled fence must never silently degrade to an UNfenced write)."""
    raw = query.get("fence", [None])[0]
    return int(raw) if raw is not None else None


# correlation-ID wire format (docs/design/observability.md): writes carry
# ``?trace=<id>`` and journal deliveries echo it back as the event's
# ``trace`` field, so one bind stays traceable scheduler -> store journal
# -> remote mirror. IDs are opaque strings, length-capped so a hostile
# query string can't bloat the store's trace ranges.
TRACE_MAX_LEN = 128


def _trace_of(query: dict):
    raw = query.get("trace", [None])[0]
    return raw[:TRACE_MAX_LEN] if raw else None


class _CountingThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that counts accepted TCP connections — the
    keep-alive regression surface: two sequential ops over one client
    connection must leave ``connections_accepted`` at 1."""

    connections_accepted = 0
    # a subscriber storm SYN-floods the stdlib default backlog of 5 —
    # connects then time out at the client even though the server is
    # healthy, which reads as a dead replica to failover clients
    request_queue_size = 1024

    def get_request(self):
        req = super().get_request()
        self.connections_accepted += 1
        return req


def _tenant_of(query: dict) -> str:
    """Tenant identity on every request (docs/design/serving.md);
    absent = the default tenant, so single-tenant deployments never
    notice the edge exists."""
    return query.get("tenant", ["default"])[0] or "default"


# native frame encoder (fastmodel.encode_object_json): resolved lazily,
# one probe per process — the guarded twin of the Python body below
_ENCODER_NATIVE = [None, False]   # [module, probed]


def _encoder_native():
    if not _ENCODER_NATIVE[1]:
        _ENCODER_NATIVE[1] = True
        try:
            from ..native.build import fastmodel
            fm = fastmodel()
            if fm is not None and hasattr(fm, "encode_object_json"):
                _ENCODER_NATIVE[0] = fm
        except Exception:
            _ENCODER_NATIVE[0] = None
    return _ENCODER_NATIVE[0]


def json_object_encoder(kind: str, o) -> bytes:
    """The hub's shared wire codec (docs/design/federation.md): one
    JSON serialization of the object payload per event per burst,
    byte-shared across every subscriber's frame. Compact separators —
    these bytes are spliced verbatim into NDJSON frame lines.

    The native fast path (``fastmodel.encode_object_json``) fuses the
    dataclass reflection walk and the compact dump into one C pass;
    byte parity with the Python body is pinned by
    tests/test_native_encoder.py, and any native miss (no toolchain,
    unencodable shape) falls through to the Python twin per object."""
    fm = _encoder_native()
    if fm is not None:
        try:
            return fm.encode_object_json(o)
        except Exception:
            pass    # unencodable shape: take the reflective path
    return json.dumps(encode_object(kind, o),
                      separators=(",", ":")).encode()


class StoreHTTPServer:
    """The apiserver seam. ``hub``/``admission`` are optional: without
    them the server behaves exactly as the pre-serving era (no
    /watchstream, no write throttling) — cmd/apiserver wires both in
    for the production multi-tenant edge.

    ``member`` (a :class:`~volcano_tpu.replication.election.
    FederationMember`) turns on federation process mode: ``/leader``
    answers leader discovery, ``/lease/<sender>`` takes peer lease
    pushes, object writes are role-gated (a follower or degraded
    replica answers a structured 503 + Retry-After + leader hint
    instead of silently forking the rv space), and follower reads are
    annotated with a staleness bound."""

    def __init__(self, store: ObjectStore, host: str = "127.0.0.1",
                 port: int = 8181, hub=None, admission=None,
                 member=None):
        self.store = store
        self.hub = hub
        self.admission = admission
        self.member = member
        if hub is not None and getattr(hub, "encoder", None) is None:
            # pre-serialize frames once per burst at the hub so the
            # watchstream fan-out shares object bytes across subscribers
            hub.encoder = json_object_encoder
        handler = self._make_handler()
        self.httpd = _CountingThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def connections_accepted(self) -> int:
        return self.httpd.connections_accepted

    def _make_handler(self):
        store = self.store
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 + accurate Content-Length (or chunked framing) on
            # EVERY response = persistent connections. The pre-serving
            # server answered HTTP/1.0-style — one request per TCP
            # connection, a fresh handshake per write on the seam that
            # carries every bind.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, payload, headers=None) -> None:
                # keep-alive hygiene: a response sent BEFORE the request
                # body was read (throttled write, unknown route, bad
                # fence) must still drain that body, or its bytes parse
                # as the connection's next request line. self.headers is
                # fresh per request, so it carries the consumed flag.
                try:
                    remaining = int(self.headers.get("Content-Length",
                                                     0) or 0)
                    if remaining and not getattr(self.headers,
                                                 "_body_consumed", False):
                        self.headers._body_consumed = True
                        self.rfile.read(remaining)
                except (ValueError, OSError):
                    self.close_connection = True
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _gate_write(self) -> bool:
                """Federation role gate on the write path: only the
                fenced leader takes writes. False = a structured 503
                with Retry-After and the current leader hint already
                went out (retry_transient honors the delay; a failover
                client re-discovers the leader from the hint)."""
                member = server.member
                if member is None or member.accepts_writes():
                    return True
                hint = member.leader_hint()
                retry_after = member.retry_after()
                payload = {"error": f"replica {member.name} is "
                                    f"{member.role()}: writes go to the "
                                    f"leader",
                           "role": member.role(),
                           "retry_after": retry_after,
                           "leader": hint}
                stale = member.staleness()
                if stale is not None:
                    payload["staleness"] = stale
                self._send(503, payload,
                           headers={"Retry-After":
                                    str(max(1, math.ceil(retry_after)))})
                return False

            def _send_read_only(self, e: ReadOnlyError) -> None:
                """Durability degradation (docs/design/durability.md):
                the WAL can no longer persist writes (ENOSPC/EIO), so
                the store answers every mutation with the same
                structured 503 + Retry-After shape the federation role
                gate uses — the client pacer already honors it."""
                retry_after = float(getattr(e, "retry_after", 5.0))
                self._send(503, {"error": str(e), "read_only": True,
                                 "reason": getattr(e, "reason", str(e)),
                                 "retry_after": retry_after},
                           headers={"Retry-After":
                                    str(max(1, math.ceil(retry_after)))})

            def _staleness_headers(self) -> Optional[dict]:
                """Read-path annotation: a non-leader replica stamps
                its role and staleness bound (applied rv + estimated
                lag) on every read so clients know how far behind the
                data may be."""
                member = server.member
                if member is None:
                    return None
                role = member.role()
                if role == "leader":
                    return None
                headers = {"X-Volcano-Role": role}
                stale = member.staleness()
                if stale is not None:
                    headers["X-Volcano-Applied-Rv"] = \
                        str(stale["applied_rv"])
                    headers["X-Volcano-Staleness-Rvs"] = \
                        str(stale["lag_rvs"])
                return headers

            def _admit_tenant(self, query: dict) -> bool:
                """Per-tenant write admission; False = throttled (the
                429 with Retry-After already went out)."""
                if server.admission is None:
                    return True
                from ..serving.admission import ThrottledError
                try:
                    server.admission.admit_write(_tenant_of(query))
                    return True
                except ThrottledError as e:
                    self._send(429, {"error": str(e),
                                     "retry_after": e.retry_after},
                               headers={"Retry-After":
                                        str(max(1, math.ceil(
                                            e.retry_after)))})
                    return False

            def _parse(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                query = urllib.parse.parse_qs(parsed.query)
                if len(parts) < 2 or parts[0] != "apis" or parts[1] not in KINDS:
                    return None
                kind = parts[1]
                rest = parts[2:]
                if kind in CLUSTER_SCOPED:
                    name = rest[0] if rest else None
                    ns = "default"
                else:
                    ns = rest[0] if len(rest) >= 2 else \
                        (query.get("namespace", ["default"])[0])
                    name = rest[1] if len(rest) >= 2 else None
                return kind, ns, name, query

            def _body(self):
                length = int(self.headers.get("Content-Length", 0))
                self.headers._body_consumed = True
                return json.loads(self.rfile.read(length)) if length else None

            def _encode_events(self, events) -> list:
                # ONE trace-map snapshot for the whole batch (a
                # 50k-event response must not copy the map per event);
                # each rv resolves by bisect
                from .store import trace_in_ranges
                ranges = store.trace_ranges() if events else []
                payload = []
                for erv, action, kind, o in events:
                    ev = {"rv": erv, "action": action, "kind": kind,
                          "object": encode_object(kind, o)}
                    trace = trace_in_ranges(ranges, erv)
                    if trace is not None:
                        ev["trace"] = trace
                    payload.append(ev)
                return payload

            def _chunk_raw(self, body: bytes) -> None:
                self.wfile.write(f"{len(body):X}\r\n".encode() + body
                                 + b"\r\n")
                self.wfile.flush()

            def _chunk(self, payload: dict) -> None:
                self._chunk_raw(json.dumps(payload).encode() + b"\n")

            def _chunk_frame_shared(self, frame: dict) -> None:
                """One event frame on the shared-bytes fast path: the
                object payloads were serialized ONCE per burst by the
                hub (``frame["encoded"]`` pairs 1:1 with the events);
                this splices the shared bytes into a per-subscriber
                wrapper carrying the per-sub action labels."""
                from .store import trace_in_ranges
                ranges = store.trace_ranges()
                parts = []
                for (erv, action, kind, _o), ob in zip(frame["events"],
                                                       frame["encoded"]):
                    head = {"rv": erv, "action": action, "kind": kind}
                    trace = trace_in_ranges(ranges, erv)
                    if trace is not None:
                        head["trace"] = trace
                    hb = json.dumps(head)
                    parts.append(hb[:-1].encode()
                                 + b', "object": ' + ob + b"}")
                meta = json.dumps({
                    "prev": frame["prev"], "from_rv": frame["from_rv"],
                    "to_rv": frame["to_rv"],
                    "coalesced_from": frame["coalesced_from"],
                    "epoch": frame.get("epoch", 0)})
                self._chunk_raw(meta[:-1].encode() + b', "events": ['
                                + b", ".join(parts) + b"]}\n")

            def _watchstream(self, q: dict) -> None:
                """Chunked streaming watch: hold the connection and
                frame coalesced batches as the hub publishes them
                (docs/design/serving.md). One frame = one chunk-framed
                NDJSON line; heartbeat pings keep half-open detection
                cheap; a cursor off the journal window gets the
                structured relist frame."""
                hub = server.hub
                if hub is None:
                    return self._send(404, {
                        "error": "watchstream not enabled (no serving "
                                 "hub on this apiserver)"})
                from ..serving.admission import ThrottledError
                try:
                    cursor = int(q.get("cursor", ["-1"])[0])
                    # clamp: heartbeat=0 would spin ping chunks at full
                    # speed off one unauthenticated request; negative
                    # would crash the Condition wait
                    heartbeat = max(1.0, min(60.0, float(
                        q.get("heartbeat", ["10"])[0])))
                except ValueError:
                    return self._send(400, {"error": "malformed cursor/"
                                                     "heartbeat"})
                client = q.get("client", [""])[0] \
                    or f"anon-{threading.get_ident()}"
                kinds_raw = q.get("kinds", [""])[0]
                kinds = tuple(k for k in kinds_raw.split(",") if k) or None
                filter_attr = None
                filt = q.get("filter", [""])[0]
                if filt:
                    # an unsupported filter must REJECT, never silently
                    # degrade to an unfiltered firehose
                    path_, eq, expected = filt.partition("=")
                    parts = path_.split(".")
                    if not eq or len(parts) != 2 or not all(parts):
                        return self._send(400, {
                            "error": f"unsupported filter {filt!r} "
                                     "(want attr0.attr1=value)"})
                    filter_attr = ((parts[0], parts[1]), expected)
                try:
                    sub = hub.subscribe(
                        client, tenant=_tenant_of(q), kinds=kinds,
                        filter_attr=filter_attr,
                        since_rv=None if cursor < 0 else cursor)
                except ThrottledError as e:
                    return self._send(
                        429, {"error": str(e),
                              "retry_after": e.retry_after},
                        headers={"Retry-After":
                                 str(max(1, math.ceil(e.retry_after)))})
                # a stream monopolizes its connection; never keep-alive
                self.close_connection = True
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    # sub.anchor, NOT sub.cursor: dispatch may already
                    # have advanced the live cursor past frames sitting
                    # in the outbox, and a hello ahead of those frames
                    # turns them into client-visible duplicates
                    hello = {"hello": True, "rv": sub.anchor,
                             "client": client, "epoch": hub.epoch}
                    member = server.member
                    if member is not None:
                        hello["role"] = member.role()
                        stale = member.staleness()
                        if stale is not None:
                            hello["staleness_rvs"] = stale["lag_rvs"]
                    self._chunk(hello)
                    while True:
                        frame = sub.next_frame(timeout=heartbeat)
                        if sub.closed:
                            break
                        if frame is None:
                            ping = {"ping": True,
                                    "rv": store.current_rv(),
                                    "epoch": hub.epoch}
                            if member is not None:
                                ping["role"] = member.role()
                                stale = member.staleness()
                                if stale is not None:
                                    ping["staleness_rvs"] = \
                                        stale["lag_rvs"]
                            self._chunk(ping)
                            continue
                        if frame.get("relist"):
                            self._chunk({"relist": True,
                                         "rv": frame["rv"],
                                         "prev": frame.get("prev"),
                                         "epoch": frame.get(
                                             "epoch", hub.epoch)})
                            continue
                        if frame.get("encoded") is not None:
                            self._chunk_frame_shared(frame)
                            continue
                        self._chunk({
                            "prev": frame["prev"],
                            "from_rv": frame["from_rv"],
                            "to_rv": frame["to_rv"],
                            "coalesced_from": frame["coalesced_from"],
                            "epoch": frame.get("epoch", hub.epoch),
                            "events": self._encode_events(
                                frame["events"])})
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass   # client went away: normal stream teardown
                finally:
                    hub.unsubscribe(sub)

            def _replicate_stream(self, q: dict) -> None:
                """Leader half of journal replication (docs/design/
                federation.md): stream contiguous journal ranges to a
                follower replica as chunked NDJSON, every frame stamped
                with this replica's newest observed leadership epoch
                (the fence floor) so a deposed leader's frames are
                rejectable at the follower. A cursor off the journal
                window answers a ``gone`` frame — the follower must
                bootstrap from ``/replicate/snapshot``."""
                try:
                    since = int(q.get("since", ["0"])[0])
                    heartbeat = max(1.0, min(60.0, float(
                        q.get("heartbeat", ["10"])[0])))
                except ValueError:
                    return self._send(400, {"error": "malformed since/"
                                                     "heartbeat"})
                self.close_connection = True
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    self._chunk({"hello": True, "rv": store.current_rv(),
                                 "epoch": store.fence_floor()})
                    cursor = since
                    while True:
                        events, rv, resync = store.events_since(
                            cursor, heartbeat)
                        if resync:
                            self._chunk({"gone": True, "rv": rv,
                                         "epoch": store.fence_floor()})
                            return
                        if not events:
                            self._chunk({"ping": True, "rv": rv,
                                         "epoch": store.fence_floor()})
                            continue
                        self._chunk({
                            "from_rv": events[0][0], "to_rv": rv,
                            "epoch": store.fence_floor(),
                            "entries": [
                                [e[0], e[1], e[2],
                                 encode_object(e[2], e[3])]
                                for e in events]})
                        cursor = rv
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass   # follower went away: normal stream teardown

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/rv":
                    return self._send(200, {"rv": store.current_rv()})
                if parsed.path == "/fence":
                    return self._send(200, {"floor": store.fence_floor()})
                if parsed.path == "/leader":
                    member = server.member
                    if member is None:
                        # standalone apiserver: it IS the write target
                        return self._send(200, {
                            "role": "standalone", "accepts_writes": True,
                            "holder": "", "url": "",
                            "token": store.fence_floor(), "live": True})
                    info = member.leader_hint()
                    info["role"] = member.role()
                    info["accepts_writes"] = member.accepts_writes()
                    stale = member.staleness()
                    if stale is not None:
                        info["staleness"] = stale
                    return self._send(200, info)
                if parsed.path == "/watchstream":
                    return self._watchstream(
                        urllib.parse.parse_qs(parsed.query))
                if parsed.path == "/replicate":
                    return self._replicate_stream(
                        urllib.parse.parse_qs(parsed.query))
                if parsed.path == "/replicate/snapshot":
                    from ..replication.leader import snapshot_payload
                    return self._send(200, snapshot_payload(store))
                if parsed.path == "/watch":
                    q = urllib.parse.parse_qs(parsed.query)
                    since = int(q.get("since", ["0"])[0])
                    timeout = min(60.0, float(q.get("timeout", ["25"])[0]))
                    events, rv, resync = store.events_since(since, timeout)
                    payload = self._encode_events(events)
                    # "gone" is the structured signal that the cursor
                    # fell off the journal window: the client MUST
                    # re-list and re-anchor at "rv" ("resync" kept for
                    # pre-serving clients — same meaning)
                    return self._send(200, {"rv": rv, "resync": resync,
                                            "gone": resync,
                                            "events": payload})
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, ns, name, query = route
                # read-path offload (docs/design/serving.md): serve from
                # live refs — encoding only READS, stored objects are
                # replaced never mutated, so the per-request deep copy
                # bought nothing but writer-lock contention
                stale_headers = self._staleness_headers()
                if name is None:
                    namespace = query.get("namespace", [None])[0]
                    items = store.list_refs(kind, namespace)
                    return self._send(200, {"items": [
                        encode_object(kind, o) for o in items]},
                        headers=stale_headers)
                o = store.get_ref(kind, name, ns)
                if o is None:
                    return self._send(404, {"error": f"{kind} {name} not found"})
                return self._send(200, encode_object(kind, o),
                                  headers=stale_headers)

            def do_POST(self):
                parsed = urllib.parse.urlparse(self.path)
                if not self._admit_tenant(
                        urllib.parse.parse_qs(parsed.query)):
                    return
                if parsed.path.startswith("/lease/"):
                    # a peer's leader lease push (process-mode election
                    # side channel — NEVER the replicated rv space)
                    member = server.member
                    if member is None:
                        return self._send(404, {
                            "error": "not a federation member"})
                    body = self._body() or {}
                    view = member.receive_lease(
                        body.get("holder", ""),
                        int(body.get("token", 0)),
                        body.get("url", ""))
                    return self._send(200, view)
                if parsed.path == "/fence":
                    # the LeaderElector of a remote process announcing its
                    # freshly-acquired token; floor advance is monotonic
                    body = self._body() or {}
                    floor = store.advance_fence(int(body.get("token", 0)))
                    return self._send(200, {"floor": floor})
                if parsed.path == "/events":
                    if not self._gate_write():
                        return
                    body = self._body()
                    o = decode_object(body["kind"], body["object"]) \
                        if body.get("object") else None
                    store.record_event(body["kind"], o, body["event_type"],
                                       body["reason"], body["message"])
                    return self._send(201, {"status": "recorded"})
                if parsed.path == "/admissionwebhooks":
                    # the webhook-manager's self-registration: the store
                    # calls back over HTTPS on matching operations,
                    # verifying the webhook's serving certificate against
                    # the registered CA bundle (the reference registers
                    # WebhookConfigurations carrying caBundle,
                    # cmd/webhook-manager/app/server.go:64-87 +
                    # util.go:37-130)
                    body = self._body()
                    from .remote import RemoteAdmissionHook
                    store.register_admission(RemoteAdmissionHook(
                        kind=body["kind"], path=body.get("path", ""),
                        url=body["url"],
                        operations=tuple(body.get("operations",
                                                  ("CREATE",))),
                        ca_bundle=body.get("ca_bundle", "")),
                        replace=True)
                    return self._send(201, {"status": "registered"})
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                if not self._gate_write():
                    return
                kind, _ns, _name, query = route
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    o = decode_object(kind, self._body())
                    created = store.create(kind, o, fence=fence,
                                           trace=_trace_of(query))
                    return self._send(201, encode_object(kind, created))
                except ReadOnlyError as e:
                    return self._send_read_only(e)
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(409, {"error": str(e)})

            def do_PUT(self):
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, _ns, _name, query = route
                if not self._admit_tenant(query):
                    return
                if not self._gate_write():
                    return
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    o = decode_object(kind, self._body())
                    updated = store.update(kind, o, fence=fence,
                                           trace=_trace_of(query))
                    return self._send(200, encode_object(kind, updated))
                except ReadOnlyError as e:
                    return self._send_read_only(e)
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except ConflictError as e:
                    return self._send(409, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(404, {"error": str(e)})

            def do_DELETE(self):
                route = self._parse()
                if route is None or route[2] is None:
                    return self._send(404, {"error": "not found"})
                kind, ns, name, query = route
                if not self._admit_tenant(query):
                    return
                if not self._gate_write():
                    return
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    rv = store.delete(kind, name, ns, fence=fence,
                                      trace=_trace_of(query))
                    return self._send(200, {"status": "deleted", "rv": rv})
                except ReadOnlyError as e:
                    return self._send_read_only(e)
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(404, {"error": str(e)})

        return Handler

    def start(self) -> threading.Thread:
        if self.hub is not None:
            self.hub.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        if self.hub is not None:
            self.hub.stop()
        self.httpd.shutdown()
        self.httpd.server_close()


class ApiError(Exception):
    def __init__(self, code: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        # the 429 edge's Retry-After, parsed so RemoteStore's backoff
        # can honor the server's own horizon instead of guessing
        self.retry_after = retry_after


class PooledConnection:
    """Per-thread persistent HTTP/1.1 connections to one base URL.

    The pre-serving client opened a fresh ``urllib.urlopen`` (TCP
    handshake + slow-start) PER WRITE — on the seam that carries every
    bind. With the server speaking HTTP/1.1 this keeps one
    ``http.client.HTTPConnection`` per (thread, endpoint) and replays a
    request once when a cached connection turns out to have been closed
    idle by the peer (``RemoteDisconnected`` before any response bytes —
    the same at-least-once caveat ``retry_transient`` documents)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        u = urllib.parse.urlsplit(base_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"PooledConnection is http-only, got "
                             f"{base_url!r}")
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = timeout
        self._local = threading.local()

    def _conn(self, fresh: bool = False):
        import http.client
        conn = getattr(self._local, "conn", None)
        if fresh and conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            self._local.conn = conn
        return conn

    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[dict] = None) -> tuple:
        """(status, headers, body bytes); retries once on a stale cached
        connection, never on a fresh one."""
        import http.client
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        for attempt in (0, 1):
            conn = self._conn(fresh=attempt > 0)
            reused = attempt == 0 and getattr(self._local, "used", False)
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                self._local.used = True
                if resp.will_close:
                    self.close()
                return resp.status, resp.headers, data
            except (http.client.RemoteDisconnected,
                    http.client.BadStatusLine,
                    http.client.CannotSendRequest,
                    BrokenPipeError, ConnectionResetError):
                self.close()
                if not reused:
                    raise
                # stale keep-alive connection: reconnect and replay once
            except BaseException:
                # ANY other failure (connection refused, timeout, ...)
                # must DROP the cached connection: http.client leaves a
                # half-started request state behind a failed connect,
                # and every later request on that object would raise
                # CannotSendRequest forever
                self.close()
                raise

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            self._local.conn = None
            self._local.used = False


class StoreClient:
    """Remote client mirroring the ObjectStore CRUD surface, over a
    pooled keep-alive connection (writes reuse one TCP connection; the
    RemoteStore watch loop streams on its own).

    ``base_url`` may be a single endpoint (the pre-federation shape —
    behavior is unchanged) or a LIST of replica endpoints. With a list
    the client fails over: a dead endpoint rotates to the next one
    (reads) or re-discovers the leader via ``GET /leader`` (writes),
    a 503 role rejection re-discovers and retries, and a 412 fence
    rejection re-discovers for the NEXT operation but re-raises —
    a fenced write is a correctness signal, never silently absorbed.
    Retry pacing shares :func:`~volcano_tpu.utils.backoff.
    seeded_backoff` with the replication follower (deterministic
    jitter, no third ad-hoc retry loop)."""

    FAILOVER_BASE_S = 0.05
    FAILOVER_CAP_S = 1.0

    def __init__(self, base_url: Union[str, Sequence[str]],
                 timeout: float = 10.0, client_id: str = ""):
        if isinstance(base_url, str):
            endpoints = [base_url]
        else:
            endpoints = list(base_url)
        if not endpoints:
            raise ValueError("StoreClient needs at least one endpoint")
        self.endpoints: List[str] = [e.rstrip("/") for e in endpoints]
        self.timeout = timeout
        self.client_id = client_id or "store-client"
        self._pools = {e: PooledConnection(e, timeout=timeout)
                       for e in self.endpoints}
        self.base_url = self.endpoints[0]
        self.pool = self._pools[self.base_url]
        self.failovers = 0
        self.leader_redirects = 0

    # -- endpoint routing --------------------------------------------------

    def _use(self, endpoint: str) -> None:
        self.base_url = endpoint
        self.pool = self._pools[endpoint]

    def _rotate(self) -> str:
        """Next endpoint in declaration order (deterministic)."""
        i = self.endpoints.index(self.base_url)
        self._use(self.endpoints[(i + 1) % len(self.endpoints)])
        return self.base_url

    def _probe_leader(self, endpoint: str) -> dict:
        status, _headers, body = self._pools[endpoint].request(
            "GET", "/leader")
        if status != 200:
            raise ApiError(status, f"leader probe: HTTP {status}")
        return json.loads(body)

    def discover_leader(self) -> Optional[str]:
        """Find the replica currently accepting writes: probe every
        endpoint (active first, then declaration order) for
        ``GET /leader``; follow a holder-url hint when it names a known
        endpoint. Returns the endpoint (now active) or None when no
        replica claims the lease (degraded set — the caller's 503
        handling paces the retry)."""
        order = [self.base_url] + [e for e in self.endpoints
                                   if e != self.base_url]
        hints: List[str] = []
        for ep in order:
            try:
                info = self._probe_leader(ep)
            except Exception:
                continue
            if info.get("accepts_writes") and info.get("role") in (
                    "leader", "standalone"):
                self._use(ep)
                return ep
            hint = (info.get("url") or "").rstrip("/")
            if hint and hint in self.endpoints and hint not in hints:
                hints.append(hint)
        for ep in hints:
            try:
                info = self._probe_leader(ep)
            except Exception:
                continue
            if info.get("accepts_writes"):
                self._use(ep)
                return ep
        return None

    def _request(self, method: str, path: str, payload=None):
        import http.client
        data = json.dumps(payload).encode() if payload is not None else None
        is_write = method in ("POST", "PUT", "DELETE")
        single = len(self.endpoints) == 1
        attempts = 1 if single else 2 * len(self.endpoints)
        last_exc: Optional[Exception] = None
        for attempt in range(1, attempts + 1):
            if attempt > 1:
                delay = seeded_backoff(
                    f"{self.client_id}:{method}:{path}", attempt - 1,
                    self.FAILOVER_BASE_S, self.FAILOVER_CAP_S)
                if delay:
                    time.sleep(delay)
            try:
                status, headers, body = self.pool.request(method, path,
                                                          body=data)
            except (OSError, http.client.HTTPException) as e:
                # keep the pre-pool error contract: connection-level
                # blips surface as URLError (what retry_transient
                # classifies)
                last_exc = urllib.error.URLError(e)
                if single:
                    raise last_exc from None
                self.failovers += 1
                if is_write:
                    self.discover_leader()
                else:
                    self._rotate()
                continue
            if status >= 400:
                try:
                    decoded = json.loads(body)
                except Exception:
                    decoded = {}
                message = decoded.get("error", "") or f"HTTP {status}"
                retry_after = None
                ra = headers.get("Retry-After") \
                    if headers is not None else None
                if ra:
                    try:
                        retry_after = float(ra)
                    except ValueError:
                        pass
                err = ApiError(status, message, retry_after=retry_after)
                if status == 412 and not single:
                    # fenced: OUR regime knowledge is stale. Re-discover
                    # so the next op routes right, but surface the
                    # rejection — a silent retry into a deposed leader
                    # (or with a dead token) is the failure mode fencing
                    # exists to stop
                    self.leader_redirects += 1
                    self.discover_leader()
                    raise err
                if status == 503 and not single and is_write \
                        and attempt < attempts:
                    # role rejection: a follower/degraded replica.
                    # Honor its Retry-After, then re-discover
                    self.failovers += 1
                    if retry_after:
                        time.sleep(min(retry_after,
                                       self.FAILOVER_CAP_S))
                    self.discover_leader()
                    last_exc = err
                    continue
                raise err
            return json.loads(body) if body else None
        raise last_exc if last_exc is not None else \
            urllib.error.URLError("no endpoint reachable")

    def _path(self, kind: str, name: Optional[str] = None,
              namespace: str = "default") -> str:
        if name is None:
            return f"/apis/{kind}"
        if kind in CLUSTER_SCOPED:
            return f"/apis/{kind}/{name}"
        return f"/apis/{kind}/{namespace}/{name}"

    def get(self, kind: str, name: str, namespace: str = "default"):
        try:
            data = self._request("GET", self._path(kind, name, namespace))
        except ApiError as e:
            if e.code == 404:
                return None
            raise
        return decode_object(kind, data)

    def list(self, kind: str, namespace: Optional[str] = None) -> list:
        path = self._path(kind)
        if namespace is not None:
            path += f"?namespace={urllib.parse.quote(namespace)}"
        data = self._request("GET", path)
        return [decode_object(kind, item) for item in data["items"]]

    @staticmethod
    def _with_params(path: str, fence, trace=None) -> str:
        params = []
        if fence is not None:
            params.append(f"fence={int(fence)}")
        if trace is not None:
            params.append(f"trace={urllib.parse.quote(str(trace))}")
        return f"{path}?{'&'.join(params)}" if params else path

    def create(self, kind: str, o, fence=None, trace=None):
        data = self._request("POST",
                             self._with_params(self._path(kind), fence,
                                               trace),
                             encode_object(kind, o))
        return decode_object(kind, data)

    def update(self, kind: str, o, fence=None, trace=None):
        path = self._path(kind, o.metadata.name, o.metadata.namespace)
        data = self._request("PUT", self._with_params(path, fence, trace),
                             encode_object(kind, o))
        return decode_object(kind, data)

    def delete(self, kind: str, name: str, namespace: str = "default",
               fence=None, trace=None):
        return self._request(
            "DELETE", self._with_params(self._path(kind, name, namespace),
                                        fence, trace))

    def advance_fence(self, token: int) -> int:
        return int(self._request("POST", "/fence",
                                 {"token": int(token)}).get("floor", 0))
