"""HTTP front-end for the object store + client.

The reference's CLI and controllers speak REST to the Kubernetes API server;
this module gives the standalone framework the same seam: a threaded HTTP
server over an :class:`ObjectStore` and a client exposing the store's CRUD
interface over the wire. Watches stay in-process (scheduler/controllers run
in the serving process; SURVEY.md section 5.8).

Routes (namespaced kinds):
    GET    /apis/{kind}?namespace=ns      list
    GET    /apis/{kind}/{ns}/{name}       get
    POST   /apis/{kind}                   create
    PUT    /apis/{kind}/{ns}/{name}       update
    DELETE /apis/{kind}/{ns}/{name}       delete
Cluster-scoped kinds use /apis/{kind}/{name}.
Admission rejections -> 422, conflicts -> 409, missing -> 404.

Serving-hub era (docs/design/serving.md): the server speaks HTTP/1.1
with keep-alive (every response carries Content-Length or chunked
framing — one TCP connection serves a client's whole write stream), an
optional :class:`~volcano_tpu.serving.hub.ServingHub` adds the chunked
``/watchstream?cursor=rv`` streaming endpoint (coalesced event-batch
frames pushed as they publish, heartbeat pings between), and an optional
:class:`~volcano_tpu.serving.admission.AdmissionController` enforces
per-tenant write rate limits at the edge — throttled writes answer a
structured 429 with Retry-After, which :class:`StoreClient` surfaces as
``ApiError.retry_after`` and RemoteStore honors in its backoff.
"""

from __future__ import annotations

import json
import math
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .codec import decode_object, encode_object
from .store import (CLUSTER_SCOPED, KINDS, AdmissionError, ConflictError,
                    FencedError, ObjectStore)


def _fence_of(query: dict):
    """Optional fencing token from a write request's query string
    (?fence=N). Fenced rejections map to HTTP 412 Precondition Failed —
    distinct from the 409 conflict, which is retryable by re-reading.
    Raises ValueError on a malformed token (handlers answer 400: a
    garbled fence must never silently degrade to an UNfenced write)."""
    raw = query.get("fence", [None])[0]
    return int(raw) if raw is not None else None


# correlation-ID wire format (docs/design/observability.md): writes carry
# ``?trace=<id>`` and journal deliveries echo it back as the event's
# ``trace`` field, so one bind stays traceable scheduler -> store journal
# -> remote mirror. IDs are opaque strings, length-capped so a hostile
# query string can't bloat the store's trace ranges.
TRACE_MAX_LEN = 128


def _trace_of(query: dict):
    raw = query.get("trace", [None])[0]
    return raw[:TRACE_MAX_LEN] if raw else None


class _CountingThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that counts accepted TCP connections — the
    keep-alive regression surface: two sequential ops over one client
    connection must leave ``connections_accepted`` at 1."""

    connections_accepted = 0

    def get_request(self):
        req = super().get_request()
        self.connections_accepted += 1
        return req


def _tenant_of(query: dict) -> str:
    """Tenant identity on every request (docs/design/serving.md);
    absent = the default tenant, so single-tenant deployments never
    notice the edge exists."""
    return query.get("tenant", ["default"])[0] or "default"


def json_object_encoder(kind: str, o) -> bytes:
    """The hub's shared wire codec (docs/design/federation.md): one
    JSON serialization of the object payload per event per burst,
    byte-shared across every subscriber's frame. Compact separators —
    these bytes are spliced verbatim into NDJSON frame lines."""
    return json.dumps(encode_object(kind, o),
                      separators=(",", ":")).encode()


class StoreHTTPServer:
    """The apiserver seam. ``hub``/``admission`` are optional: without
    them the server behaves exactly as the pre-serving era (no
    /watchstream, no write throttling) — cmd/apiserver wires both in
    for the production multi-tenant edge."""

    def __init__(self, store: ObjectStore, host: str = "127.0.0.1",
                 port: int = 8181, hub=None, admission=None):
        self.store = store
        self.hub = hub
        self.admission = admission
        if hub is not None and getattr(hub, "encoder", None) is None:
            # pre-serialize frames once per burst at the hub so the
            # watchstream fan-out shares object bytes across subscribers
            hub.encoder = json_object_encoder
        handler = self._make_handler()
        self.httpd = _CountingThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def connections_accepted(self) -> int:
        return self.httpd.connections_accepted

    def _make_handler(self):
        store = self.store
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 + accurate Content-Length (or chunked framing) on
            # EVERY response = persistent connections. The pre-serving
            # server answered HTTP/1.0-style — one request per TCP
            # connection, a fresh handshake per write on the seam that
            # carries every bind.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, payload, headers=None) -> None:
                # keep-alive hygiene: a response sent BEFORE the request
                # body was read (throttled write, unknown route, bad
                # fence) must still drain that body, or its bytes parse
                # as the connection's next request line. self.headers is
                # fresh per request, so it carries the consumed flag.
                try:
                    remaining = int(self.headers.get("Content-Length",
                                                     0) or 0)
                    if remaining and not getattr(self.headers,
                                                 "_body_consumed", False):
                        self.headers._body_consumed = True
                        self.rfile.read(remaining)
                except (ValueError, OSError):
                    self.close_connection = True
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _admit_tenant(self, query: dict) -> bool:
                """Per-tenant write admission; False = throttled (the
                429 with Retry-After already went out)."""
                if server.admission is None:
                    return True
                from ..serving.admission import ThrottledError
                try:
                    server.admission.admit_write(_tenant_of(query))
                    return True
                except ThrottledError as e:
                    self._send(429, {"error": str(e),
                                     "retry_after": e.retry_after},
                               headers={"Retry-After":
                                        str(max(1, math.ceil(
                                            e.retry_after)))})
                    return False

            def _parse(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                query = urllib.parse.parse_qs(parsed.query)
                if len(parts) < 2 or parts[0] != "apis" or parts[1] not in KINDS:
                    return None
                kind = parts[1]
                rest = parts[2:]
                if kind in CLUSTER_SCOPED:
                    name = rest[0] if rest else None
                    ns = "default"
                else:
                    ns = rest[0] if len(rest) >= 2 else \
                        (query.get("namespace", ["default"])[0])
                    name = rest[1] if len(rest) >= 2 else None
                return kind, ns, name, query

            def _body(self):
                length = int(self.headers.get("Content-Length", 0))
                self.headers._body_consumed = True
                return json.loads(self.rfile.read(length)) if length else None

            def _encode_events(self, events) -> list:
                # ONE trace-map snapshot for the whole batch (a
                # 50k-event response must not copy the map per event);
                # each rv resolves by bisect
                from .store import trace_in_ranges
                ranges = store.trace_ranges() if events else []
                payload = []
                for erv, action, kind, o in events:
                    ev = {"rv": erv, "action": action, "kind": kind,
                          "object": encode_object(kind, o)}
                    trace = trace_in_ranges(ranges, erv)
                    if trace is not None:
                        ev["trace"] = trace
                    payload.append(ev)
                return payload

            def _chunk_raw(self, body: bytes) -> None:
                self.wfile.write(f"{len(body):X}\r\n".encode() + body
                                 + b"\r\n")
                self.wfile.flush()

            def _chunk(self, payload: dict) -> None:
                self._chunk_raw(json.dumps(payload).encode() + b"\n")

            def _chunk_frame_shared(self, frame: dict) -> None:
                """One event frame on the shared-bytes fast path: the
                object payloads were serialized ONCE per burst by the
                hub (``frame["encoded"]`` pairs 1:1 with the events);
                this splices the shared bytes into a per-subscriber
                wrapper carrying the per-sub action labels."""
                from .store import trace_in_ranges
                ranges = store.trace_ranges()
                parts = []
                for (erv, action, kind, _o), ob in zip(frame["events"],
                                                       frame["encoded"]):
                    head = {"rv": erv, "action": action, "kind": kind}
                    trace = trace_in_ranges(ranges, erv)
                    if trace is not None:
                        head["trace"] = trace
                    hb = json.dumps(head)
                    parts.append(hb[:-1].encode()
                                 + b', "object": ' + ob + b"}")
                meta = json.dumps({
                    "prev": frame["prev"], "from_rv": frame["from_rv"],
                    "to_rv": frame["to_rv"],
                    "coalesced_from": frame["coalesced_from"],
                    "epoch": frame.get("epoch", 0)})
                self._chunk_raw(meta[:-1].encode() + b', "events": ['
                                + b", ".join(parts) + b"]}\n")

            def _watchstream(self, q: dict) -> None:
                """Chunked streaming watch: hold the connection and
                frame coalesced batches as the hub publishes them
                (docs/design/serving.md). One frame = one chunk-framed
                NDJSON line; heartbeat pings keep half-open detection
                cheap; a cursor off the journal window gets the
                structured relist frame."""
                hub = server.hub
                if hub is None:
                    return self._send(404, {
                        "error": "watchstream not enabled (no serving "
                                 "hub on this apiserver)"})
                from ..serving.admission import ThrottledError
                try:
                    cursor = int(q.get("cursor", ["-1"])[0])
                    # clamp: heartbeat=0 would spin ping chunks at full
                    # speed off one unauthenticated request; negative
                    # would crash the Condition wait
                    heartbeat = max(1.0, min(60.0, float(
                        q.get("heartbeat", ["10"])[0])))
                except ValueError:
                    return self._send(400, {"error": "malformed cursor/"
                                                     "heartbeat"})
                client = q.get("client", [""])[0] \
                    or f"anon-{threading.get_ident()}"
                kinds_raw = q.get("kinds", [""])[0]
                kinds = tuple(k for k in kinds_raw.split(",") if k) or None
                filter_attr = None
                filt = q.get("filter", [""])[0]
                if filt:
                    # an unsupported filter must REJECT, never silently
                    # degrade to an unfiltered firehose
                    path_, eq, expected = filt.partition("=")
                    parts = path_.split(".")
                    if not eq or len(parts) != 2 or not all(parts):
                        return self._send(400, {
                            "error": f"unsupported filter {filt!r} "
                                     "(want attr0.attr1=value)"})
                    filter_attr = ((parts[0], parts[1]), expected)
                try:
                    sub = hub.subscribe(
                        client, tenant=_tenant_of(q), kinds=kinds,
                        filter_attr=filter_attr,
                        since_rv=None if cursor < 0 else cursor)
                except ThrottledError as e:
                    return self._send(
                        429, {"error": str(e),
                              "retry_after": e.retry_after},
                        headers={"Retry-After":
                                 str(max(1, math.ceil(e.retry_after)))})
                # a stream monopolizes its connection; never keep-alive
                self.close_connection = True
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    self._chunk({"hello": True, "rv": sub.cursor,
                                 "client": client, "epoch": hub.epoch})
                    while True:
                        frame = sub.next_frame(timeout=heartbeat)
                        if sub.closed:
                            break
                        if frame is None:
                            self._chunk({"ping": True,
                                         "rv": store.current_rv(),
                                         "epoch": hub.epoch})
                            continue
                        if frame.get("relist"):
                            self._chunk({"relist": True,
                                         "rv": frame["rv"],
                                         "prev": frame.get("prev"),
                                         "epoch": frame.get(
                                             "epoch", hub.epoch)})
                            continue
                        if frame.get("encoded") is not None:
                            self._chunk_frame_shared(frame)
                            continue
                        self._chunk({
                            "prev": frame["prev"],
                            "from_rv": frame["from_rv"],
                            "to_rv": frame["to_rv"],
                            "coalesced_from": frame["coalesced_from"],
                            "epoch": frame.get("epoch", hub.epoch),
                            "events": self._encode_events(
                                frame["events"])})
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass   # client went away: normal stream teardown
                finally:
                    hub.unsubscribe(sub)

            def _replicate_stream(self, q: dict) -> None:
                """Leader half of journal replication (docs/design/
                federation.md): stream contiguous journal ranges to a
                follower replica as chunked NDJSON, every frame stamped
                with this replica's newest observed leadership epoch
                (the fence floor) so a deposed leader's frames are
                rejectable at the follower. A cursor off the journal
                window answers a ``gone`` frame — the follower must
                bootstrap from ``/replicate/snapshot``."""
                try:
                    since = int(q.get("since", ["0"])[0])
                    heartbeat = max(1.0, min(60.0, float(
                        q.get("heartbeat", ["10"])[0])))
                except ValueError:
                    return self._send(400, {"error": "malformed since/"
                                                     "heartbeat"})
                self.close_connection = True
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    self._chunk({"hello": True, "rv": store.current_rv(),
                                 "epoch": store.fence_floor()})
                    cursor = since
                    while True:
                        events, rv, resync = store.events_since(
                            cursor, heartbeat)
                        if resync:
                            self._chunk({"gone": True, "rv": rv,
                                         "epoch": store.fence_floor()})
                            return
                        if not events:
                            self._chunk({"ping": True, "rv": rv,
                                         "epoch": store.fence_floor()})
                            continue
                        self._chunk({
                            "from_rv": events[0][0], "to_rv": rv,
                            "epoch": store.fence_floor(),
                            "entries": [
                                [e[0], e[1], e[2],
                                 encode_object(e[2], e[3])]
                                for e in events]})
                        cursor = rv
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass   # follower went away: normal stream teardown

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/rv":
                    return self._send(200, {"rv": store.current_rv()})
                if parsed.path == "/fence":
                    return self._send(200, {"floor": store.fence_floor()})
                if parsed.path == "/watchstream":
                    return self._watchstream(
                        urllib.parse.parse_qs(parsed.query))
                if parsed.path == "/replicate":
                    return self._replicate_stream(
                        urllib.parse.parse_qs(parsed.query))
                if parsed.path == "/replicate/snapshot":
                    from ..replication.leader import snapshot_payload
                    return self._send(200, snapshot_payload(store))
                if parsed.path == "/watch":
                    q = urllib.parse.parse_qs(parsed.query)
                    since = int(q.get("since", ["0"])[0])
                    timeout = min(60.0, float(q.get("timeout", ["25"])[0]))
                    events, rv, resync = store.events_since(since, timeout)
                    payload = self._encode_events(events)
                    # "gone" is the structured signal that the cursor
                    # fell off the journal window: the client MUST
                    # re-list and re-anchor at "rv" ("resync" kept for
                    # pre-serving clients — same meaning)
                    return self._send(200, {"rv": rv, "resync": resync,
                                            "gone": resync,
                                            "events": payload})
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, ns, name, query = route
                # read-path offload (docs/design/serving.md): serve from
                # live refs — encoding only READS, stored objects are
                # replaced never mutated, so the per-request deep copy
                # bought nothing but writer-lock contention
                if name is None:
                    namespace = query.get("namespace", [None])[0]
                    items = store.list_refs(kind, namespace)
                    return self._send(200, {"items": [
                        encode_object(kind, o) for o in items]})
                o = store.get_ref(kind, name, ns)
                if o is None:
                    return self._send(404, {"error": f"{kind} {name} not found"})
                return self._send(200, encode_object(kind, o))

            def do_POST(self):
                parsed = urllib.parse.urlparse(self.path)
                if not self._admit_tenant(
                        urllib.parse.parse_qs(parsed.query)):
                    return
                if parsed.path == "/fence":
                    # the LeaderElector of a remote process announcing its
                    # freshly-acquired token; floor advance is monotonic
                    body = self._body() or {}
                    floor = store.advance_fence(int(body.get("token", 0)))
                    return self._send(200, {"floor": floor})
                if parsed.path == "/events":
                    body = self._body()
                    o = decode_object(body["kind"], body["object"]) \
                        if body.get("object") else None
                    store.record_event(body["kind"], o, body["event_type"],
                                       body["reason"], body["message"])
                    return self._send(201, {"status": "recorded"})
                if parsed.path == "/admissionwebhooks":
                    # the webhook-manager's self-registration: the store
                    # calls back over HTTPS on matching operations,
                    # verifying the webhook's serving certificate against
                    # the registered CA bundle (the reference registers
                    # WebhookConfigurations carrying caBundle,
                    # cmd/webhook-manager/app/server.go:64-87 +
                    # util.go:37-130)
                    body = self._body()
                    from .remote import RemoteAdmissionHook
                    store.register_admission(RemoteAdmissionHook(
                        kind=body["kind"], path=body.get("path", ""),
                        url=body["url"],
                        operations=tuple(body.get("operations",
                                                  ("CREATE",))),
                        ca_bundle=body.get("ca_bundle", "")),
                        replace=True)
                    return self._send(201, {"status": "registered"})
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, _ns, _name, query = route
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    o = decode_object(kind, self._body())
                    created = store.create(kind, o, fence=fence,
                                           trace=_trace_of(query))
                    return self._send(201, encode_object(kind, created))
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(409, {"error": str(e)})

            def do_PUT(self):
                route = self._parse()
                if route is None:
                    return self._send(404, {"error": "not found"})
                kind, _ns, _name, query = route
                if not self._admit_tenant(query):
                    return
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    o = decode_object(kind, self._body())
                    updated = store.update(kind, o, fence=fence,
                                           trace=_trace_of(query))
                    return self._send(200, encode_object(kind, updated))
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except ConflictError as e:
                    return self._send(409, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(404, {"error": str(e)})

            def do_DELETE(self):
                route = self._parse()
                if route is None or route[2] is None:
                    return self._send(404, {"error": "not found"})
                kind, ns, name, query = route
                if not self._admit_tenant(query):
                    return
                try:
                    fence = _fence_of(query)
                except ValueError:
                    return self._send(400, {"error": "malformed fence token"})
                try:
                    rv = store.delete(kind, name, ns, fence=fence,
                                      trace=_trace_of(query))
                    return self._send(200, {"status": "deleted", "rv": rv})
                except FencedError as e:
                    return self._send(412, {"error": str(e)})
                except AdmissionError as e:
                    return self._send(422, {"error": str(e)})
                except KeyError as e:
                    return self._send(404, {"error": str(e)})

        return Handler

    def start(self) -> threading.Thread:
        if self.hub is not None:
            self.hub.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        if self.hub is not None:
            self.hub.stop()
        self.httpd.shutdown()
        self.httpd.server_close()


class ApiError(Exception):
    def __init__(self, code: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        # the 429 edge's Retry-After, parsed so RemoteStore's backoff
        # can honor the server's own horizon instead of guessing
        self.retry_after = retry_after


class PooledConnection:
    """Per-thread persistent HTTP/1.1 connections to one base URL.

    The pre-serving client opened a fresh ``urllib.urlopen`` (TCP
    handshake + slow-start) PER WRITE — on the seam that carries every
    bind. With the server speaking HTTP/1.1 this keeps one
    ``http.client.HTTPConnection`` per (thread, endpoint) and replays a
    request once when a cached connection turns out to have been closed
    idle by the peer (``RemoteDisconnected`` before any response bytes —
    the same at-least-once caveat ``retry_transient`` documents)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        u = urllib.parse.urlsplit(base_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"PooledConnection is http-only, got "
                             f"{base_url!r}")
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = timeout
        self._local = threading.local()

    def _conn(self, fresh: bool = False):
        import http.client
        conn = getattr(self._local, "conn", None)
        if fresh and conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            self._local.conn = conn
        return conn

    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[dict] = None) -> tuple:
        """(status, headers, body bytes); retries once on a stale cached
        connection, never on a fresh one."""
        import http.client
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        for attempt in (0, 1):
            conn = self._conn(fresh=attempt > 0)
            reused = attempt == 0 and getattr(self._local, "used", False)
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                self._local.used = True
                if resp.will_close:
                    self.close()
                return resp.status, resp.headers, data
            except (http.client.RemoteDisconnected,
                    http.client.BadStatusLine,
                    http.client.CannotSendRequest,
                    BrokenPipeError, ConnectionResetError):
                self.close()
                if not reused:
                    raise
                # stale keep-alive connection: reconnect and replay once
            except BaseException:
                # ANY other failure (connection refused, timeout, ...)
                # must DROP the cached connection: http.client leaves a
                # half-started request state behind a failed connect,
                # and every later request on that object would raise
                # CannotSendRequest forever
                self.close()
                raise

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            self._local.conn = None
            self._local.used = False


class StoreClient:
    """Remote client mirroring the ObjectStore CRUD surface, over a
    pooled keep-alive connection (writes reuse one TCP connection; the
    RemoteStore watch loop streams on its own)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.pool = PooledConnection(self.base_url, timeout=timeout)

    def _request(self, method: str, path: str, payload=None):
        import http.client
        data = json.dumps(payload).encode() if payload is not None else None
        try:
            status, headers, body = self.pool.request(method, path,
                                                      body=data)
        except (OSError, http.client.HTTPException) as e:
            # keep the pre-pool error contract: connection-level blips
            # surface as URLError (what retry_transient classifies)
            raise urllib.error.URLError(e) from None
        if status >= 400:
            try:
                message = json.loads(body).get("error", "")
            except Exception:
                message = ""
            message = message or f"HTTP {status}"
            retry_after = None
            ra = headers.get("Retry-After") if headers is not None else None
            if ra:
                try:
                    retry_after = float(ra)
                except ValueError:
                    pass
            raise ApiError(status, message, retry_after=retry_after)
        return json.loads(body) if body else None

    def _path(self, kind: str, name: Optional[str] = None,
              namespace: str = "default") -> str:
        if name is None:
            return f"/apis/{kind}"
        if kind in CLUSTER_SCOPED:
            return f"/apis/{kind}/{name}"
        return f"/apis/{kind}/{namespace}/{name}"

    def get(self, kind: str, name: str, namespace: str = "default"):
        try:
            data = self._request("GET", self._path(kind, name, namespace))
        except ApiError as e:
            if e.code == 404:
                return None
            raise
        return decode_object(kind, data)

    def list(self, kind: str, namespace: Optional[str] = None) -> list:
        path = self._path(kind)
        if namespace is not None:
            path += f"?namespace={urllib.parse.quote(namespace)}"
        data = self._request("GET", path)
        return [decode_object(kind, item) for item in data["items"]]

    @staticmethod
    def _with_params(path: str, fence, trace=None) -> str:
        params = []
        if fence is not None:
            params.append(f"fence={int(fence)}")
        if trace is not None:
            params.append(f"trace={urllib.parse.quote(str(trace))}")
        return f"{path}?{'&'.join(params)}" if params else path

    def create(self, kind: str, o, fence=None, trace=None):
        data = self._request("POST",
                             self._with_params(self._path(kind), fence,
                                               trace),
                             encode_object(kind, o))
        return decode_object(kind, data)

    def update(self, kind: str, o, fence=None, trace=None):
        path = self._path(kind, o.metadata.name, o.metadata.namespace)
        data = self._request("PUT", self._with_params(path, fence, trace),
                             encode_object(kind, o))
        return decode_object(kind, data)

    def delete(self, kind: str, name: str, namespace: str = "default",
               fence=None, trace=None):
        return self._request(
            "DELETE", self._with_params(self._path(kind, name, namespace),
                                        fence, trace))

    def advance_fence(self, token: int) -> int:
        return int(self._request("POST", "/fence",
                                 {"token": int(token)}).get("floor", 0))
