"""Durable write-ahead journal for the apiserver store.

The federation layer (docs/design/federation.md) made the control plane
survive replica kills only because a LIVE PEER holds the state — every
replica's journal and object map are RAM-only. This module is the
single-node durability story (docs/design/durability.md): a segmented
append-only write-ahead log that persists every journal entry batch the
sequencer publishes, plus snapshot-anchored compaction reusing the
persistence.py snapshot format.

Design points (the doc has the full protocol):

- **Riding the sequencer.** The store forwards every run of journal
  entries that lands on the contiguous tail (``_journal_extend_locked``)
  to :meth:`WriteAheadLog.append_entries` — a 50k-bind flush arrives as
  ONE call and lands as ONE group-committed record range. The call is
  O(1) under the store lock (it enqueues object REFS; stored objects are
  replaced, never mutated, so deferred encoding off-lock is safe).
- **Record framing.** ``<u32 length><u32 crc32(payload)><payload>``,
  payload compact JSON. Record types: ``seg`` (segment header), ``e``
  (entry batch: ``[[rv, action, kind, encoded_obj], ...]``), ``f``
  (fence-floor advance, so recovery re-anchors the write fence).
- **Group commit.** A flusher thread (or the sim's deterministic
  :meth:`pump`) drains pending batches, writes them as records and
  issues ONE fsync per drain, bounded by ``flush_interval``. Writers
  never wait on fsync: the durability contract is "at most
  ``flush_interval`` of acked writes lost on power failure", exactly
  the etcd default a Volcano deployment delegates to.
- **Generations.** A snapshot-install (follower bootstrap) REPLACES the
  rv space, so segments from before it must never replay over the new
  snapshot. Every cutover bumps a generation counter; segments carry it
  in their name and recovery only replays segments whose generation
  matches the snapshot's.
- **Degradation.** ENOSPC/EIO on append or fsync flips the attached
  store read-only (writes answer structured 503 + Retry-After at the
  HTTP edge); a failed record write is truncated away so a later retry
  cannot leave garbage mid-log. fsync failure is terminal for the
  process lifetime (post-failure page-cache state is unknowable —
  the fsyncgate lesson).

Crash injection (the durability-smoke gate): ``VOLCANO_WAL_CRASH`` set
to ``<point>:<n>`` SIGKILLs the process at the n-th crossing of that
injection point (``pre-fsync``, ``post-fsync-pre-rename``,
``mid-compaction``) — a REAL kill, no atexit, no flush.
"""

from __future__ import annotations

import errno
import io
import json
import os
import re
import signal
import struct
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .codec import decode_object, encode_object

# native record encoder (fastmodel.encode_object_json): the group-commit
# flusher serializes whole entry-batch records — dataclass walk + compact
# dump fused into one C pass, byte-identical to the
# encode_object/json.dumps pair below (parity pinned by
# tests/test_native_encoder.py). Resolved lazily; any miss falls back to
# the Python twin per record.
_ENC_NATIVE = [None, False]   # [module, probed]


def _enc_native():
    if not _ENC_NATIVE[1]:
        _ENC_NATIVE[1] = True
        try:
            from ..native.build import fastmodel
            fm = fastmodel()
            if fm is not None and hasattr(fm, "encode_object_json"):
                _ENC_NATIVE[0] = fm
        except Exception:
            _ENC_NATIVE[0] = None
    return _ENC_NATIVE[0]


_HEADER = struct.Struct("<II")
_SEGMENT_RE = re.compile(r"^wal-g(\d+)-s(\d+)-(\d+)\.log$")

#: crash-point counters for VOLCANO_WAL_CRASH=<point>:<n> (process-local;
#: the smoke harness sets the env on the child it intends to kill)
_CRASH_HITS: Dict[str, int] = {}


def _maybe_crash(point: str) -> None:
    spec = os.environ.get("VOLCANO_WAL_CRASH", "")
    if not spec:
        return
    want, _, nth = spec.partition(":")
    if want != point:
        return
    _CRASH_HITS[point] = _CRASH_HITS.get(point, 0) + 1
    if _CRASH_HITS[point] >= max(1, int(nth or 1)):
        os.kill(os.getpid(), signal.SIGKILL)   # no cleanup, no flush


def _metrics():
    try:
        from ..metrics import metrics as _m
        return _m
    except Exception:
        return None


class WalCorruptionError(Exception):
    """Mid-log corruption: a record that fails its CRC (or breaks rv
    contiguity) with durable records after it. Recovery REFUSES — the
    evidence (segment, byte offset, expected/got CRC) rides on the
    exception so the operator sees exactly what is damaged."""

    def __init__(self, message: str, segment: str = "", offset: int = -1,
                 expected_crc: Optional[int] = None,
                 got_crc: Optional[int] = None):
        super().__init__(message)
        self.segment = segment
        self.offset = offset
        self.expected_crc = expected_crc
        self.got_crc = got_crc


def _fsync_dir(path: str) -> None:
    """fsync the directory entry so a create/rename/unlink is durable,
    not just the file bytes (POSIX crash-consistency requires both)."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _default_opener(path: str):
    # unbuffered append-binary: one write() syscall per record blob
    # lint: allow(durability): this IS the sanctioned WAL append opener
    return open(path, "ab", buffering=0)


def pack_record(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


class _SegmentReader:
    """Sequential record reader over one segment file with the
    torn-tail / mid-log-corruption distinction:

    - an incomplete header, an incomplete payload, or a CRC mismatch on
      the FINAL record of the file is a torn write → report truncation
      offset and stop;
    - a CRC mismatch followed by another well-formed record is a bit
      flip mid-log → :class:`WalCorruptionError` with the evidence.
    """

    def __init__(self, path: str):
        self.path = path
        self.truncate_at: Optional[int] = None
        self.records: List[dict] = []

    def scan(self) -> "_SegmentReader":
        with open(self.path, "rb") as f:
            data = f.read()
        size = len(data)
        off = 0
        while off < size:
            if off + _HEADER.size > size:
                self.truncate_at = off          # torn header
                break
            length, crc = _HEADER.unpack_from(data, off)
            end = off + _HEADER.size + length
            if end > size:
                self.truncate_at = off          # torn payload
                break
            payload = data[off + _HEADER.size:end]
            got = zlib.crc32(payload)
            if got != crc:
                if self._well_formed_after(data, end):
                    raise WalCorruptionError(
                        f"WAL record at {self.path}:{off} fails CRC "
                        f"(expected {crc:#010x}, got {got:#010x}) with "
                        f"valid records after it — refusing to replay "
                        f"a damaged log",
                        segment=self.path, offset=off,
                        expected_crc=crc, got_crc=got)
                self.truncate_at = off          # torn final record
                break
            try:
                self.records.append(json.loads(payload))
            except ValueError:
                raise WalCorruptionError(
                    f"WAL record at {self.path}:{off} passes CRC but is "
                    f"not JSON — framing damage", segment=self.path,
                    offset=off, expected_crc=crc, got_crc=got)
            off = end
        return self

    @staticmethod
    def _well_formed_after(data: bytes, off: int) -> bool:
        size = len(data)
        if off + _HEADER.size > size:
            return False
        length, crc = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + length
        if end > size:
            return False
        return zlib.crc32(data[off + _HEADER.size:end]) == crc


class WriteAheadLog:
    """Segmented group-commit write-ahead log bound to one ObjectStore.

    Lifecycle: construct over a data dir, :meth:`attach` to the store
    (which starts forwarding journal-tail advances here), then either
    :meth:`start` the background flusher (process mode) or drive
    :meth:`pump` deterministically (sim / tests). :meth:`close` flushes,
    optionally compacts, and releases the segment file.
    """

    SNAPSHOT_NAME = "snapshot.json"

    def __init__(self, data_dir: str, flush_interval: float = 0.05,
                 segment_max_bytes: int = 64 * 1024 * 1024,
                 compact_interval: float = 30.0,
                 opener: Optional[Callable] = None,
                 on_degrade: Optional[Callable] = None):
        self.data_dir = os.path.abspath(data_dir)
        os.makedirs(self.data_dir, exist_ok=True)
        self.flush_interval = float(flush_interval)
        self.segment_max_bytes = int(segment_max_bytes)
        self.compact_interval = float(compact_interval)
        self._opener = opener or _default_opener
        self._on_degrade = on_degrade
        self.store = None
        # Three locks, strictly ordered _flush_serial -> _io -> _lock
        # (never the reverse):
        #
        # - _lock/_cond guard ONLY the pending queue + flusher wakeup
        #   flags. Enqueue runs under the STORE lock, so nothing held
        #   here may ever block on file I/O (writers must not wait on
        #   fsync) or call back into the store (ABBA deadlock: a writer
        #   holding the store lock blocks in append_entries while the
        #   flusher holding a WAL lock blocks in enter_read_only).
        # - _io guards the segment file + durability cursor; write +
        #   fsync happen under it.
        # - _flush_serial serializes whole flushes: two concurrent
        #   flushes draining separate batches and racing to the file
        #   write would land records out of rv order — a gap to the
        #   recovery scan.
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._io = threading.Lock()
        self._flush_serial = threading.Lock()
        self._pending: deque = deque()      # ("e", entries) | ("f", token)
        self._pending_entries = 0
        self._file: Optional[io.IOBase] = None
        self._segment_path = ""
        self._segment_bytes = 0
        self._generation = 0
        self._seq = 0
        self._durable_rv = 0
        self._reset_to: Optional[int] = None   # snapshot-install cutover
        self._compact_requested = False
        self._degraded: Optional[str] = None
        self._fsync_poisoned = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_compact = time.perf_counter()
        # telemetry rings (perf_counter durations — never decisions)
        self._fsync_ms: deque = deque(maxlen=2048)
        self._append_ms: deque = deque(maxlen=4096)
        self.records_written = 0
        self.entries_written = 0
        self.fsyncs = 0
        self.flushes = 0
        self.compactions = 0
        self.rotations = 0
        self.append_errors = 0

    # -- wiring ------------------------------------------------------------

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.data_dir, self.SNAPSHOT_NAME)

    def attach(self, store) -> None:
        """Bind to ``store`` and open the active segment at its current
        tail. Call AFTER recovery installed state (attach is the cutover
        from replay mode to append mode)."""
        self.store = store
        gen, seq = _max_gen_seq(self.data_dir)
        with self._io:
            self._generation = gen
            self._seq = seq
            self._durable_rv = store.current_rv()
            self._open_segment_locked(self._durable_rv)
        store.attach_wal(self)
        set_active(self)

    # -- store-side hooks (called under the STORE lock: O(1) only) ---------

    def append_entries(self, entries) -> None:
        """Enqueue one contiguous run of journal entries (refs — the
        flusher encodes off-lock). Called by the sequencer on every
        journal-tail advance."""
        if self._fsync_poisoned:
            return      # terminal: the queue would never drain again
        t0 = time.perf_counter()
        with self._cond:
            self._pending.append(("e", entries))
            self._pending_entries += len(entries)
            self._cond.notify()
        self._append_ms.append((time.perf_counter() - t0) * 1000.0)
        m = _metrics()
        if m is not None:
            m.inc(m.WAL_APPENDS)

    def append_fence(self, token: int) -> None:
        if self._fsync_poisoned:
            return      # terminal: the queue would never drain again
        with self._cond:
            self._pending.append(("f", int(token)))
            self._cond.notify()

    def on_snapshot_installed(self, rv: int) -> None:
        """A snapshot install (follower bootstrap) replaced the rv
        space: drop pre-install pending batches and schedule a
        generation cutover. Called under the store lock — flag-setting
        only; the flusher performs the cutover off-lock."""
        with self._cond:
            self._pending.clear()
            self._pending_entries = 0
            self._reset_to = int(rv)
            self._compact_requested = True
            self._cond.notify()

    def request_compact(self) -> None:
        with self._cond:
            self._compact_requested = True
            self._cond.notify()

    # -- flusher -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="wal-flusher")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                # while degraded the pending queue stays non-empty (the
                # failed batch is re-enqueued) — wait the interval
                # anyway so ENOSPC retries are paced, not a spin
                if self._degraded is not None \
                        or (not self._pending
                            and not self._compact_requested):
                    self._cond.wait(timeout=self.flush_interval)
            try:
                self.pump()
            except Exception:
                pass        # degradation is recorded; never kill the loop

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def close(self, final_compact: bool = False) -> None:
        self.stop()
        try:
            if final_compact and self._degraded is None:
                self.compact()
            else:
                self.flush()
        finally:
            with self._io:
                if self._file is not None:
                    try:
                        self._file.close()
                    except OSError:
                        pass
                    self._file = None

    def pump(self) -> int:
        """One deterministic flusher round: cutover if scheduled, flush
        pending, compact when due. The sim drives this from the virtual
        clock; the background thread calls it per wakeup. Returns the
        number of entries made durable."""
        reset = None
        with self._lock:
            if self._reset_to is not None:
                reset = self._reset_to
                self._reset_to = None
        if reset is not None:
            self._cutover(reset)
        n = self.flush()
        due = (time.perf_counter() - self._last_compact
               >= self.compact_interval > 0)
        with self._lock:
            requested = self._compact_requested
            self._compact_requested = False
        if requested or due:
            self.compact()
        return n

    # -- the write path ----------------------------------------------------

    def flush(self) -> int:
        """Drain pending batches into the active segment as records and
        group-commit them with one fsync. Returns entries persisted.
        Whole flushes serialize (the group-commit thread and a manual
        caller must not interleave their drained batches on disk)."""
        with self._flush_serial:
            return self._flush_serialized()

    def _flush_serialized(self) -> int:
        with self._cond:
            if not self._pending or self._fsync_poisoned:
                return 0
            batch = list(self._pending)
            self._pending.clear()
            self._pending_entries = 0
        records: List[bytes] = []
        hi_rv = self._durable_rv
        n_entries = 0
        fm = _enc_native()
        for kind_tag, payload in batch:
            if kind_tag == "f":
                records.append(pack_record(json.dumps(
                    {"t": "f", "token": payload},
                    separators=(",", ":")).encode()))
                continue
            entries = payload
            rec = None
            if fm is not None:
                try:
                    # one C pass over the raw objects: the dataclass
                    # walk and the compact dump fused, byte-identical
                    # to the Python pair below
                    rec = fm.encode_object_json(
                        {"t": "e", "lo": entries[0][0],
                         "hi": entries[-1][0],
                         "e": [[rv, action, k, o]
                               for rv, action, k, o in entries]})
                except Exception:
                    rec = None   # unencodable shape: reflective path
            if rec is None:
                enc = [[rv, action, k, encode_object(k, o)]
                       for rv, action, k, o in entries]
                rec = json.dumps(
                    {"t": "e", "lo": entries[0][0],
                     "hi": entries[-1][0], "e": enc},
                    separators=(",", ":")).encode()
            records.append(pack_record(rec))
            hi_rv = max(hi_rv, entries[-1][0])
            n_entries += len(entries)
        blob = b"".join(records)
        t0 = time.perf_counter()
        fail_reason = None
        with self._io:
            if self._fsync_poisoned:
                return 0
            start_size = self._segment_bytes
            try:
                if self._file is None:
                    self._open_segment_locked(self._durable_rv)
                self._file.write(blob)
                self._segment_bytes += len(blob)
                _maybe_crash("pre-fsync")
                self._do_fsync_locked()
            except OSError as e:
                fail_reason = self._handle_write_error_locked(
                    e, start_size)
                poisoned = self._fsync_poisoned
            else:
                self._durable_rv = hi_rv
                self.records_written += len(records)
                self.entries_written += n_entries
                self.flushes += 1
                if self._segment_bytes >= self.segment_max_bytes:
                    self._rotate_locked(self._durable_rv)
        if fail_reason is not None:
            # off _io: the store call in _notify_degrade takes the
            # store lock, which a writer blocked in append_entries may
            # hold — acquiring it under a WAL lock would ABBA-deadlock
            with self._cond:
                if poisoned:
                    # nothing will ever drain again — don't leak
                    self._pending.clear()
                    self._pending_entries = 0
                else:
                    # re-enqueue the drained batch at the FRONT: the
                    # segment was wound back to a clean prefix, so the
                    # retry after an ENOSPC heal re-lands the same
                    # records in the same order and recovery never
                    # sees an rv gap
                    self._pending.extendleft(reversed(batch))
                    self._pending_entries += n_entries
            self._notify_degrade(fail_reason)
            return 0
        self._fsync_ms.append((time.perf_counter() - t0) * 1000.0)
        self._heal()
        m = _metrics()
        if m is not None:
            m.inc(m.WAL_RECORDS, len(records))
            m.inc(m.WAL_ENTRIES, n_entries)
            m.observe(m.WAL_FSYNC_MS, self._fsync_ms[-1])
            m.set_gauge(m.WAL_DURABLE_RV, self._durable_rv)
        return n_entries

    def _do_fsync_locked(self) -> None:
        f = self._file
        if hasattr(f, "fsync"):
            f.fsync()               # fault-injecting file layer seam
        else:
            os.fsync(f.fileno())
        self.fsyncs += 1
        m = _metrics()
        if m is not None:
            m.inc(m.WAL_FSYNCS)

    def _handle_write_error_locked(self, e: OSError,
                                   start_size: int) -> str:
        """A failed append must never leave a torn record MID-log: wind
        the segment back to the pre-record size so the log stays a clean
        prefix. Records the degraded state (caller holds ``_io``) and
        returns the reason — the caller notifies the store OFF the WAL
        locks (enter_read_only takes the store lock, which a writer
        blocked in append_entries may hold)."""
        self.append_errors += 1
        if e.errno not in (errno.ENOSPC, errno.EDQUOT):
            # EIO / unknown: durability of already-written bytes is
            # unknowable after a failed fsync — poison the log
            self._fsync_poisoned = True
        try:
            if self._file is not None:
                os.ftruncate(self._file.fileno(), start_size)
                self._segment_bytes = start_size
        except OSError:
            self._fsync_poisoned = True
        reason = (f"WAL append failed: [{errno.errorcode.get(e.errno, e.errno)}] "
                  f"{e.strerror or e}")
        self._degraded = reason
        return reason

    def _degrade(self, reason: str) -> None:
        with self._io:
            self._degraded = reason
        self._notify_degrade(reason)

    def _notify_degrade(self, reason: str) -> None:
        """Propagate a recorded degradation. MUST be called with no WAL
        lock held: enter_read_only acquires the store lock."""
        if self.store is not None:
            self.store.enter_read_only(reason)
        if self._on_degrade is not None:
            try:
                self._on_degrade(reason)
            except Exception:
                pass
        m = _metrics()
        if m is not None:
            m.set_gauge(m.WAL_READ_ONLY, 1)

    def _heal(self) -> None:
        """A successful full flush after an ENOSPC episode (space was
        freed) lifts the read-only gate; a poisoned fsync never heals.
        Store notification runs off the WAL locks (same deadlock rule
        as _notify_degrade)."""
        with self._io:
            if self._degraded is None or self._fsync_poisoned:
                return
            self._degraded = None
        if self.store is not None:
            self.store.exit_read_only()
        m = _metrics()
        if m is not None:
            m.set_gauge(m.WAL_READ_ONLY, 0)

    # -- segments ----------------------------------------------------------

    def _segment_name(self, base_rv: int) -> str:
        return (f"wal-g{self._generation}-s{self._seq:06d}-"
                f"{base_rv}.log")

    def _open_segment_locked(self, base_rv: int) -> None:
        self._seq += 1
        path = os.path.join(self.data_dir, self._segment_name(base_rv))
        self._file = self._opener(path)
        self._segment_path = path
        self._segment_bytes = 0
        header = pack_record(json.dumps(
            {"t": "seg", "v": 1, "gen": self._generation,
             "base": base_rv}, separators=(",", ":")).encode())
        self._file.write(header)
        self._segment_bytes += len(header)
        _fsync_dir(self.data_dir)
        m = _metrics()
        if m is not None:
            m.set_gauge(m.WAL_SEGMENTS, len(self.segments()))

    def _rotate_locked(self, base_rv: int) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        self._open_segment_locked(base_rv)
        self.rotations += 1

    def segments(self) -> List[str]:
        out = []
        for name in os.listdir(self.data_dir):
            if _SEGMENT_RE.match(name):
                out.append(name)
        return sorted(out, key=_segment_sort_key)

    def _cutover(self, rv: int) -> None:
        """Generation bump after a snapshot install: new segments, new
        snapshot, old generation's files purged (their rv space is
        dead). Runs on the flusher thread, off the store lock."""
        with self._io:
            self._generation += 1
            self._durable_rv = rv
            self._fsync_poisoned = False
            self._rotate_locked(rv)

    def compact(self) -> int:
        """Snapshot-anchored compaction: flush, save a durable snapshot
        of the attached store (atomic tmp+rename; the WAL is truncated
        only AFTER the snapshot fsyncs), then delete segments made
        redundant by the anchor. Returns the anchor rv."""
        if self.store is None or self._degraded is not None:
            return self._durable_rv
        from .persistence import save_store_anchored
        self.flush()
        with self._io:
            self._rotate_locked(self._durable_rv)
        try:
            # settle=True: anchoring at the raw allocation counter
            # mid-bulk would place still-publishing shards BELOW the
            # anchor — recovery would skip them and compaction would
            # prune their segments (silent loss)
            _, anchor = save_store_anchored(
                self.store, self.snapshot_path, fsync=True,
                extra={"wal_generation": self._generation},
                settle=True)
        except OSError as e:
            self._degrade(f"WAL compaction snapshot failed: {e}")
            return self._durable_rv
        _maybe_crash("mid-compaction")
        # every non-active segment is either from a dead generation or
        # covers rvs <= anchor (the rotate above happened pre-snapshot,
        # and the snapshot state is a superset of everything durable at
        # that point) — delete oldest-first so a crash mid-purge leaves
        # a contiguous suffix
        active = os.path.basename(self._segment_path)
        for name in self.segments():
            if name == active:
                continue
            gen, _seq, base = _segment_sort_key(name)
            if gen < self._generation or base <= anchor:
                try:
                    os.unlink(os.path.join(self.data_dir, name))
                except OSError:
                    pass
        _fsync_dir(self.data_dir)
        self.compactions += 1
        self._last_compact = time.perf_counter()
        m = _metrics()
        if m is not None:
            m.inc(m.WAL_COMPACTIONS)
            m.set_gauge(m.WAL_SEGMENTS, len(self.segments()))
        return anchor

    # -- reporting ---------------------------------------------------------

    def _p(self, ring: deque, q: float) -> float:
        if not ring:
            return 0.0
        vals = sorted(ring)
        return round(vals[min(len(vals) - 1,
                              int(q * (len(vals) - 1)))], 3)

    def report(self) -> dict:
        segs = self.segments()
        seg_bytes = 0
        for name in segs:
            try:
                seg_bytes += os.path.getsize(
                    os.path.join(self.data_dir, name))
            except OSError:
                pass
        with self._lock:
            pending = self._pending_entries
        with self._io:
            durable = self._durable_rv
        store_rv = self.store.current_rv() if self.store is not None \
            else 0
        return {
            "data_dir": self.data_dir,
            "attached": self.store is not None,
            "read_only": self._degraded is not None,
            "degraded_reason": self._degraded,
            "generation": self._generation,
            "durable_rv": durable,
            "store_rv": store_rv,
            "lag_entries": max(0, store_rv - durable) + pending,
            "pending_entries": pending,
            "segments": len(segs),
            "segment_bytes": seg_bytes,
            "records_written": self.records_written,
            "entries_written": self.entries_written,
            "flushes": self.flushes,
            "fsyncs": self.fsyncs,
            "fsync_p50_ms": self._p(self._fsync_ms, 0.50),
            "fsync_p99_ms": self._p(self._fsync_ms, 0.99),
            "append_p99_ms": self._p(self._append_ms, 0.99),
            "rotations": self.rotations,
            "compactions": self.compactions,
            "append_errors": self.append_errors,
            "flush_interval": self.flush_interval,
            "compact_interval": self.compact_interval,
        }


def _segment_sort_key(name: str) -> Tuple[int, int, int]:
    m = _SEGMENT_RE.match(name)
    return (int(m.group(1)), int(m.group(2)), int(m.group(3)))


def _max_gen_seq(data_dir: str) -> Tuple[int, int]:
    gen = seq = 0
    try:
        names = os.listdir(data_dir)
    except OSError:
        return 0, 0
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            g, s = int(m.group(1)), int(m.group(2))
            if (g, s) > (gen, seq):
                gen, seq = g, s
    return gen, seq


# ---------------------------------------------------------------------------
# recovery
# ---------------------------------------------------------------------------


def recover_store(data_dir: str, store=None, clock=None) -> tuple:
    """Replay snapshot + WAL tail into ``store`` (or a fresh one),
    rv-preserving. Returns ``(store, report)``.

    Decision table (docs/design/durability.md):

    - no snapshot, no segments → fresh empty store;
    - snapshot only (legacy ``--data-dir`` layout) → install at its
      recorded rv;
    - snapshot + segments → install, then replay every record of the
      snapshot's WAL generation whose entries are above the anchor;
      entry runs must extend the anchor contiguously;
    - torn final record (short header/payload, or CRC-fail with nothing
      durable after it) → truncated away, replay continues with the
      clean prefix;
    - CRC-fail mid-log → :class:`WalCorruptionError` (refuse loudly).

    The rv sequencer re-anchors at the last replayed rv and the fence
    floor at max(snapshot floor, replayed fence records) — a recovering
    federation replica resumes from LOCAL state and only falls back to
    peer snapshot bootstrap when its log is behind or damaged.
    """
    from .persistence import load_snapshot_payload
    from .store import ObjectStore
    if store is None:
        store = ObjectStore(clock=clock) if clock is not None \
            else ObjectStore()
    t0 = time.perf_counter()
    report = {"data_dir": os.path.abspath(data_dir), "snapshot_rv": 0,
              "snapshot_objects": 0, "generation": 0,
              "segments_scanned": 0, "records_replayed": 0,
              "entries_replayed": 0, "torn_records_truncated": 0,
              "truncated_bytes": 0, "fence_floor": 0, "final_rv": 0}
    snap_path = os.path.join(data_dir, WriteAheadLog.SNAPSHOT_NAME)
    anchor = 0
    generation = 0
    fence_floor = 0
    if os.path.exists(snap_path):
        payload = load_snapshot_payload(snap_path)
        anchor = int(payload.get("resource_version", 0))
        generation = int(payload.get("wal_generation", 0))
        fence_floor = int(payload.get("fence_floor", 0))
        objects: Dict[str, dict] = {}
        count = 0
        for kind, items in payload.get("objects", {}).items():
            bucket = objects.setdefault(kind, {})
            for data in items:
                o = decode_object(kind, data)
                bucket[store.key_of(kind, o)] = o
                count += 1
        store.install_snapshot(objects, anchor)
        report["snapshot_rv"] = anchor
        report["snapshot_objects"] = count
        report["generation"] = generation

    seg_names = []
    if os.path.isdir(data_dir):
        seg_names = sorted((n for n in os.listdir(data_dir)
                            if _SEGMENT_RE.match(n)),
                           key=_segment_sort_key)
    expected = anchor + 1
    for name in seg_names:
        gen, _seq, _base = _segment_sort_key(name)
        if gen != generation:
            continue        # dead generation (pre-bootstrap rv space)
        path = os.path.join(data_dir, name)
        reader = _SegmentReader(path).scan()
        report["segments_scanned"] += 1
        for rec in reader.records:
            t = rec.get("t")
            if t == "seg":
                continue
            if t == "f":
                fence_floor = max(fence_floor, int(rec.get("token", 0)))
                report["records_replayed"] += 1
                continue
            if t != "e":
                continue
            entries = []
            for rv, action, kind, data in rec["e"]:
                rv = int(rv)
                if rv <= anchor:
                    continue        # below the snapshot anchor
                if rv != expected and not entries and rv <= expected - 1:
                    continue
                if entries and rv != entries[-1][0] + 1:
                    # a CRC-valid record is still one contiguous run by
                    # construction — an interior gap is framing damage,
                    # never silently absorbed
                    raise WalCorruptionError(
                        f"WAL rv gap inside record in {path}: "
                        f"{entries[-1][0]} followed by {rv} — refusing "
                        f"to replay a damaged log", segment=path)
                entries.append((rv, action, kind,
                                decode_object(kind, data)))
            if not entries:
                continue
            if entries[0][0] != expected:
                raise WalCorruptionError(
                    f"WAL gap in {path}: expected rv {expected}, "
                    f"record starts at {entries[0][0]} — a segment "
                    f"below it is missing or damaged",
                    segment=path)
            try:
                store.apply_replicated(entries)
            except Exception as e:
                raise WalCorruptionError(
                    f"WAL replay failed in {path}: {e}",
                    segment=path) from e
            expected = entries[-1][0] + 1
            report["records_replayed"] += 1
            report["entries_replayed"] += len(entries)
        if reader.truncate_at is not None:
            # torn tail: only the final segment may carry one — a torn
            # record with durable segments after it is mid-log damage
            if name != seg_names[-1]:
                raise WalCorruptionError(
                    f"torn record at {path}:{reader.truncate_at} in a "
                    f"non-final segment — refusing to replay",
                    segment=path, offset=reader.truncate_at)
            size = os.path.getsize(path)
            report["torn_records_truncated"] += 1
            report["truncated_bytes"] += size - reader.truncate_at
            # lint: allow(durability): recovery truncating the torn WAL tail
            with open(path, "rb+") as f:
                f.truncate(reader.truncate_at)
                os.fsync(f.fileno())
    if fence_floor:
        store.advance_fence(fence_floor)
    report["fence_floor"] = fence_floor
    report["final_rv"] = store.current_rv()
    report["recovery_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
    m = _metrics()
    if m is not None:
        m.inc(m.WAL_RECOVERIES)
        if report["torn_records_truncated"]:
            m.inc(m.WAL_TORN_TRUNCATIONS,
                  report["torn_records_truncated"])
    _LAST_RECOVERY.clear()
    _LAST_RECOVERY.update(report)
    return store, report


# ---------------------------------------------------------------------------
# active-WAL registry (the /debug/durability + vcctl surface)
# ---------------------------------------------------------------------------

_ACTIVE: Dict[str, Optional[WriteAheadLog]] = {"wal": None}
_LAST_RECOVERY: dict = {}


def set_active(wal: Optional[WriteAheadLog]) -> None:
    _ACTIVE["wal"] = wal


def durability_report() -> dict:
    """The /debug/durability payload: the active WAL's report (or an
    unattached stub) plus the last recovery's verdict."""
    wal = _ACTIVE["wal"]
    if wal is None:
        out = {"attached": False, "read_only": False}
    else:
        out = wal.report()
    if _LAST_RECOVERY:
        out["last_recovery"] = dict(_LAST_RECOVERY)
    return out


__all__ = ["WriteAheadLog", "WalCorruptionError", "recover_store",
           "durability_report", "set_active", "pack_record"]
