"""Store persistence: checkpoint/restore of the whole control-plane state.

The reference's control-plane durability is the etcd-backed CRD store —
every component is stateless and rebuilds from the API server on restart
(SURVEY.md section 5.4). Here the ObjectStore is in-memory, so this module
provides the same guarantee: serialize every object (via the JSON codec) to
a snapshot file, and restore it into a fresh store on startup. Watches fire
during restore exactly like an informer's initial list, so caches and
controllers rebuild their state identically to a live replay.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

from .codec import decode_object, encode_object
from .store import KINDS, ObjectStore

SNAPSHOT_VERSION = 1


def save_store(store: ObjectStore, path: str, fsync: bool = False) -> int:
    """Write an atomic snapshot; returns the number of objects saved.

    Safe to call while a sharded bulk patch has rvs reserved but
    unpublished (parked journal entries, non-contiguous tail): the
    snapshot is taken under the store lock, records the ALLOCATION
    counter ``_rv`` (not the journal tail), and object data committed by
    interleaved writers — even writers whose journal entry is still
    parked behind the reservation — is captured. Restore re-anchors the
    sequencer at that counter, so a snapshot mid-flight never loses
    writes or replays a torn journal (tests/test_failover.py,
    TestParkedJournalRestore).

    ``fsync=True`` makes the snapshot crash-durable (file fsynced
    before the rename, directory fsynced after) — the WAL compaction
    contract (docs/design/durability.md) requires it; the periodic
    checkpointer keeps the cheap page-cache write."""
    count, _rv = save_store_anchored(store, path, fsync=fsync)
    return count


def save_store_anchored(store: ObjectStore, path: str,
                        fsync: bool = False,
                        extra: Optional[dict] = None,
                        settle: bool = False) -> tuple:
    """:func:`save_store` returning ``(count, anchor_rv)`` — the rv the
    payload actually recorded, which WAL compaction needs to decide
    which segments the snapshot supersedes. ``extra`` merges additional
    top-level keys into the payload (the WAL stamps its generation and
    the store's fence floor rides along for recovery re-anchoring).

    ``settle=True`` waits for the journal settle barrier before reading
    the anchor. The plain checkpointer path deliberately tolerates a
    mid-flight anchor (the rv counter may be ahead of published
    content), but WAL compaction must NOT: it prunes every segment at
    or below the anchor, so a mid-bulk anchor taken above
    still-publishing shards would silently drop those entries from
    both the snapshot and the log. The settle wait releases the store
    lock while blocked, so in-flight shard publishes finish rather
    than deadlock."""
    payload = {"version": SNAPSHOT_VERSION, "resource_version": 0,
               "objects": {}}
    if extra:
        payload.update(extra)
    count = 0
    with store._lock:
        if settle:
            store._wait_journal_settled_locked()
        anchor = payload["resource_version"] = store._rv
        payload["fence_floor"] = store._fence_floor
        for kind in sorted(KINDS):
            items = list(store._objects[kind].values())
            payload["objects"][kind] = [encode_object(kind, o) for o in items]
            count += len(items)
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".snapshot-")
    try:
        # lint: allow(durability): tmp-file write inside the atomic-rename helper
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        if fsync:
            from .wal import _maybe_crash
            _maybe_crash("post-fsync-pre-rename")
        # lint: allow(durability): this IS the sanctioned atomic-rename helper
        os.replace(tmp, path)   # atomic on POSIX
        if fsync:
            from .wal import _fsync_dir
            _fsync_dir(directory)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return count, anchor


def load_snapshot_payload(path: str) -> dict:
    """Read + version-check a snapshot file without installing it (the
    WAL recovery path installs rv-preserving itself)."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"unsupported snapshot version "
                         f"{payload.get('version')!r}")
    return payload


def load_store(path: str, store: Optional[ObjectStore] = None,
               clock=None):
    """Restore a snapshot into ``store`` (or a new one). Objects replay
    through create with admission skipped (they were admitted when first
    written), firing watches like an informer's initial list.

    Returns (store, object_count). The change journal is cleared after the
    replay: the replayed creates carry restart-local rvs that misrepresent
    history, and remote watchers from before the restart must see a
    journal gap (resync) rather than silently missing events.

    The write-fence floor (docs/design/failover.md) is deliberately NOT
    part of a snapshot — it is incarnation-local state that re-derives
    from the lease object's persisted ``fencingToken`` at the next
    acquisition (the lease ConfigMap itself IS snapshotted). A restorer
    that must close the window before that acquisition carries the old
    floor over explicitly (sim/engine.py _swap_store_from_snapshot)."""
    payload = load_snapshot_payload(path)
    if store is None:
        store = ObjectStore(clock=clock) if clock is not None else ObjectStore()
    count = 0
    for kind, items in payload["objects"].items():
        if kind not in KINDS:
            continue
        for data in items:
            o = decode_object(kind, data)
            store.create(kind, o, skip_admission=True)
            count += 1
    with store._lock:
        store._rv = max(store._rv, int(payload.get("resource_version", 0)))
        store._journal.clear()
        # re-anchor the journal sequencer at the restored rv: the cleared
        # journal window starts fresh (clients resync on the gap) and no
        # parked entries can refer to pre-restore reservations
        store._journal_tail = store._rv
        store._journal_parked.clear()
    return store, count


class StoreCheckpointer:
    """Periodic snapshotting (the etcd WAL-interval equivalent)."""

    def __init__(self, store: ObjectStore, path: str, interval: float = 30.0):
        self.store = store
        self.path = path
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def checkpoint(self) -> int:
        return save_store(self.store, self.path)

    def start(self) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                # interval <= 0 means shutdown-checkpoint only (a zero
                # wait would busy-spin full-store serializations)
                self._stop.wait(self.interval if self.interval > 0
                                else None)
                if not self._stop.is_set():
                    try:
                        self.checkpoint()
                    except Exception:
                        pass   # next interval retries; state stays in memory
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self._thread

    def stop(self, final_checkpoint: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            # an in-flight periodic checkpoint must not finish AFTER the
            # final one and clobber it with older state
            self._thread.join(timeout=30.0)
            self._thread = None
        if final_checkpoint:
            try:
                self.checkpoint()
            except Exception:
                pass
