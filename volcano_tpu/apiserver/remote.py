"""Multi-process deployment seam: a remote, watchable store client.

The reference deploys three binaries against the Kubernetes API server
(installer/volcano-development.yaml): informers watch-stream state in, and
writes go out as REST calls. :class:`RemoteStore` gives the standalone
framework the same topology over :mod:`volcano_tpu.apiserver.http`:

* a local mirror ``ObjectStore`` is primed by a full list and kept current
  by a long-poll watch thread (`GET /watch?since=rv` against the serving
  process's change journal) — scheduler cache / controllers register their
  watches on the mirror exactly as they would in-process;
* writes (create/update/delete/events) are REST calls to the serving
  process, where admission runs (including webhook-manager callbacks,
  :class:`RemoteAdmissionHook`);
* a journal gap (client slower than the journal window) triggers a full
  re-list, like an informer's resync after watch expiry.

Deployment recipe: docs/deployment.md; e2e proof: tests/test_multiprocess.py.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Optional

from ..utils.fastclone import fast_clone
from .codec import decode_object, encode_object
from .http import StoreClient
from .store import CLUSTER_SCOPED as _CLUSTER_SCOPED
from .store import KINDS, AdmissionError, ObjectStore

log = logging.getLogger(__name__)


class RemoteAdmissionHook:
    """Server-side half of a remotely-registered webhook: POSTs the
    admission review to the webhook-manager's endpoint and applies the
    verdict (and any mutation) — the apiserver->webhook TLS call, with
    the serving certificate verified against the webhook configuration's
    CA bundle (the reference's caBundle trust bootstrap,
    cmd/webhook-manager/app/util.go:37-130)."""

    def __init__(self, kind: str, url: str, path: str = "",
                 operations: tuple = ("CREATE",), timeout: float = 10.0,
                 ca_bundle: str = ""):
        self.kind = kind
        self.path = path
        self.url = url
        self.operations = operations
        self.timeout = timeout
        self.validate = None   # the combined review runs in mutate()
        self._ssl_ctx = None
        if url.startswith("https"):
            import ssl
            if ca_bundle:
                # trust exactly the registered CA (hostname/IP-SAN checks
                # stay on — the serving cert carries the endpoint's SANs)
                self._ssl_ctx = ssl.create_default_context(
                    cadata=ca_bundle)
            else:
                # https endpoint registered without a bundle: system trust
                self._ssl_ctx = ssl.create_default_context()

    def mutate(self, operation: str, new_obj, old_obj=None) -> None:
        payload = {
            "path": self.path, "kind": self.kind, "operation": operation,
            "object": encode_object(self.kind, new_obj)
            if new_obj is not None else None,
            "old": encode_object(self.kind, old_obj)
            if old_obj is not None else None,
        }
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl_ctx) as resp:
                review = json.loads(resp.read().decode())
        except Exception as e:
            # failurePolicy: Fail (the reference's default for its
            # validating webhooks) — an unreachable webhook rejects
            raise AdmissionError(
                f"admission webhook {self.path!r} unreachable: {e}")
        if not review.get("allowed", False):
            raise AdmissionError(review.get("message", "denied"))
        mutated = review.get("object")
        if mutated is not None and new_obj is not None:
            patched = decode_object(self.kind, mutated)
            new_obj.__dict__.update(patched.__dict__)


class RemoteStore:
    """ObjectStore-compatible facade over a remote apiserver process."""

    def __init__(self, base_url: str, poll_timeout: float = 25.0):
        self.client = StoreClient(base_url)
        self.base_url = base_url.rstrip("/")
        self.mirror = ObjectStore()
        self.poll_timeout = poll_timeout
        self._rv = 0
        # read-your-writes: a component must observe its own successful
        # writes immediately (the in-process store's synchronous watches
        # gave controllers exactly that; without it, get+mutate+update
        # round trips conflict against the component's own lagging
        # mirror). Successful writes self-apply to the mirror; the poll
        # stream's redeliveries are deduped by server resource_version.
        self._seen: dict = {}
        self._seen_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resync()
        self.events = self.mirror.events   # local event record view

    # -- sync loop ---------------------------------------------------------

    def _resync(self) -> None:
        """Prime (or re-prime) the mirror with a full list per kind.

        List+watch anchoring: the SERVER's current rv is read FIRST, the
        lists reflect state at or after it, and the poll resumes from that
        anchor — replayed events older than a listed object's server rv
        are skipped by the _seen dedup (the mirror stamps its own local
        rvs, which must never be confused with the server's)."""
        try:
            resp = json.loads(urllib.request.urlopen(
                f"{self.base_url}/rv", timeout=10.0).read().decode())
            anchor = int(resp.get("rv", 0))
        except Exception:
            log.exception("rv anchor fetch failed during resync")
            anchor = self._rv
        for kind in KINDS:
            try:
                remote = {self.mirror.key_of(kind, o): o
                          for o in self.client.list(kind)}
            except Exception:
                log.exception("list %s failed during resync", kind)
                continue
            with self.mirror._lock:
                local_keys = set(self.mirror._objects[kind])
            for key in local_keys - set(remote):
                ns, _, name = key.rpartition("/")
                with self._seen_lock:
                    self._seen[(kind, key)] = max(
                        self._seen.get((kind, key), 0), anchor)
                try:
                    self.mirror.delete(kind, name, ns or "default",
                                       skip_admission=True)
                except KeyError:
                    pass
            for key, o in remote.items():
                self._apply("MODIFIED" if key in local_keys else "ADDED",
                            kind, o, o.metadata.resource_version)
        self._rv = max(self._rv, anchor)

    def _apply(self, action: str, kind: str, o, rv: int = 0) -> None:
        key = self.mirror.key_of(kind, o)
        with self._seen_lock:
            if rv and self._seen.get((kind, key), 0) >= rv:
                return   # already applied (self-write or newer event)
            if rv:
                self._seen[(kind, key)] = rv
        if action == "DELETED":
            try:
                self.mirror.delete(kind, o.metadata.name,
                                   o.metadata.namespace, skip_admission=True)
            except KeyError:
                pass
            return
        with self.mirror._lock:
            exists = key in self.mirror._objects[kind]
        try:
            if exists:
                o.metadata.resource_version = 0   # mirror manages its own rv
                self.mirror.update(kind, o, skip_admission=True)
            else:
                self.mirror.create(kind, o, skip_admission=True)
        except KeyError:
            log.exception("mirror apply %s %s failed", action, kind)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            url = (f"{self.base_url}/watch?since={self._rv}"
                   f"&timeout={self.poll_timeout}")
            try:
                with urllib.request.urlopen(
                        url, timeout=self.poll_timeout + 10.0) as resp:
                    data = json.loads(resp.read().decode())
            except Exception:
                if not self._stop.is_set():
                    log.warning("watch poll failed; retrying", exc_info=True)
                    self._stop.wait(1.0)
                continue
            if data.get("resync"):
                self._resync()
                self._rv = max(self._rv, int(data.get("rv", self._rv)))
                continue
            for ev in data.get("events", []):
                o = decode_object(ev["kind"], ev["object"])
                self._apply(ev["action"], ev["kind"], o, int(ev["rv"]))
                self._rv = max(self._rv, int(ev["rv"]))

    def run(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="remote-store-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- ObjectStore interface ---------------------------------------------

    key_of = staticmethod(ObjectStore.key_of)

    @property
    def clock(self):
        return self.mirror.clock

    @staticmethod
    def _map_error(e):
        """HTTP status -> the in-process store's exception types, so
        controllers' retry/conflict handling works unchanged."""
        from .http import ApiError
        from .store import ConflictError
        if isinstance(e, ApiError):
            if e.code == 409 and "resource_version" in e.message:
                return ConflictError(e.message)
            if e.code in (404, 409):
                return KeyError(e.message)
            if e.code == 422:
                return AdmissionError(e.message)
        return e

    def create(self, kind: str, o, skip_admission: bool = False):
        try:
            created = self.client.create(kind, o)
        except Exception as e:
            raise self._map_error(e) from None
        # the in-process store stamps uid/rv on the caller's object in
        # place; callers chain writes on the same object, so mirror that
        # contract (otherwise the very next update conflicts on rv)
        o.metadata.uid = created.metadata.uid
        o.metadata.creation_timestamp = created.metadata.creation_timestamp
        o.metadata.resource_version = created.metadata.resource_version
        # the mirror gets its own copy: _apply restamps mirror-local rvs
        # and retains the instance, and the caller's returned object must
        # keep the authoritative server rv untouched
        self._apply("ADDED", kind, fast_clone(created),
                    created.metadata.resource_version)
        return created

    def update(self, kind: str, o, skip_admission: bool = False):
        try:
            updated = self.client.update(kind, o)
        except Exception as e:
            raise self._map_error(e) from None
        o.metadata.resource_version = updated.metadata.resource_version
        self._apply("MODIFIED", kind, fast_clone(updated),
                    updated.metadata.resource_version)
        return updated

    def delete(self, kind: str, name: str, namespace: str = "default",
               skip_admission: bool = False):
        try:
            resp = self.client.delete(kind, name, namespace)
        except Exception as e:
            raise self._map_error(e) from None
        rv = int((resp or {}).get("rv", 0)) if isinstance(resp, dict) else 0
        with self._seen_lock:
            if rv:
                key = name if kind in _CLUSTER_SCOPED else                     f"{namespace}/{name}"
                self._seen[(kind, key)] = rv
        try:
            self.mirror.delete(kind, name, namespace, skip_admission=True)
        except KeyError:
            pass

    def get(self, kind: str, name: str, namespace: str = "default"):
        # reads go to the source of truth: controllers do get+mutate+update
        # round trips that need the live resource_version
        return self.client.get(kind, name, namespace)

    def list(self, kind: str, namespace=None) -> list:
        return self.client.list(kind, namespace)

    def watch(self, kind: str, on_add=None, on_update=None, on_delete=None,
              filter_fn=None, sync: bool = True, on_bulk_update=None):
        # bulk delivery is an in-process fast path; the remote mirror
        # replays journal events one at a time, so bulk subscribers simply
        # receive per-event on_update calls (same semantics)
        return self.mirror.watch(kind, on_add, on_update, on_delete,
                                 filter_fn, sync)

    def unwatch(self, w) -> None:
        self.mirror.unwatch(w)

    def register_admission(self, hook) -> None:
        raise NotImplementedError(
            "admission hooks register on the serving process; run a "
            "webhook-manager with --server to register remotely")

    def record_event(self, kind: str, o, event_type: str, reason: str,
                     message: str) -> None:
        payload = {"kind": kind,
                   "object": encode_object(kind, o) if o is not None else None,
                   "event_type": event_type, "reason": reason,
                   "message": message}
        req = urllib.request.Request(
            f"{self.base_url}/events", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=10.0).close()
        except Exception:
            log.warning("event record failed", exc_info=True)
