"""Multi-process deployment seam: a remote, watchable store client.

The reference deploys three binaries against the Kubernetes API server
(installer/volcano-development.yaml): informers watch-stream state in, and
writes go out as REST calls. :class:`RemoteStore` gives the standalone
framework the same topology over :mod:`volcano_tpu.apiserver.http`:

* a local mirror ``ObjectStore`` is primed by a full list and kept current
  by a long-poll watch thread (`GET /watch?since=rv` against the serving
  process's change journal) — scheduler cache / controllers register their
  watches on the mirror exactly as they would in-process;
* writes (create/update/delete/events) are REST calls to the serving
  process, where admission runs (including webhook-manager callbacks,
  :class:`RemoteAdmissionHook`);
* a journal gap (client slower than the journal window) triggers a full
  re-list, like an informer's resync after watch expiry.

Deployment recipe: docs/deployment.md; e2e proof: tests/test_multiprocess.py.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Optional

from ..utils.backoff import seeded_backoff
from ..utils.fastclone import fast_clone
from .codec import decode_object, encode_object
from .http import ApiError, StoreClient
from .store import CLUSTER_SCOPED as _CLUSTER_SCOPED
from .store import KINDS, AdmissionError, ObjectStore

log = logging.getLogger(__name__)

# HTTP statuses worth retrying: the server hiccuped, not the request.
# Everything else (404/409/412/422) is a semantic verdict that a replay
# would only repeat. 429 is transient BY CONTRACT (docs/design/
# serving.md): the admission edge says "later", names the horizon in
# Retry-After, and retry_transient honors it as the backoff floor.
_TRANSIENT_CODES = frozenset({429, 500, 502, 503, 504})


def _is_transient(e: Exception) -> bool:
    if isinstance(e, ApiError):
        return e.code in _TRANSIENT_CODES
    # connection refused/reset, DNS blips, timeouts — urllib wraps them
    # all in URLError (HTTPError is an ApiError by the time it's here)
    return isinstance(e, (urllib.error.URLError, TimeoutError,
                          ConnectionError))


class _StreamUnsupported(Exception):
    """/watchstream answered 404: a pre-serving server — downgrade to
    the long-poll transport without a backoff cycle."""


class RemoteAdmissionHook:
    """Server-side half of a remotely-registered webhook: POSTs the
    admission review to the webhook-manager's endpoint and applies the
    verdict (and any mutation) — the apiserver->webhook TLS call, with
    the serving certificate verified against the webhook configuration's
    CA bundle (the reference's caBundle trust bootstrap,
    cmd/webhook-manager/app/util.go:37-130)."""

    def __init__(self, kind: str, url: str, path: str = "",
                 operations: tuple = ("CREATE",), timeout: float = 10.0,
                 ca_bundle: str = ""):
        self.kind = kind
        self.path = path
        self.url = url
        self.operations = operations
        self.timeout = timeout
        self.validate = None   # the combined review runs in mutate()
        self._ssl_ctx = None
        if url.startswith("https"):
            import ssl
            if ca_bundle:
                # trust exactly the registered CA (hostname/IP-SAN checks
                # stay on — the serving cert carries the endpoint's SANs)
                self._ssl_ctx = ssl.create_default_context(
                    cadata=ca_bundle)
            else:
                # https endpoint registered without a bundle: system trust
                self._ssl_ctx = ssl.create_default_context()

    def mutate(self, operation: str, new_obj, old_obj=None) -> None:
        payload = {
            "path": self.path, "kind": self.kind, "operation": operation,
            "object": encode_object(self.kind, new_obj)
            if new_obj is not None else None,
            "old": encode_object(self.kind, old_obj)
            if old_obj is not None else None,
        }
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl_ctx) as resp:
                review = json.loads(resp.read().decode())
        except Exception as e:
            # failurePolicy: Fail (the reference's default for its
            # validating webhooks) — an unreachable webhook rejects
            raise AdmissionError(
                f"admission webhook {self.path!r} unreachable: {e}")
        if not review.get("allowed", False):
            raise AdmissionError(review.get("message", "denied"))
        mutated = review.get("object")
        if mutated is not None and new_obj is not None:
            patched = decode_object(self.kind, mutated)
            new_obj.__dict__.update(patched.__dict__)


def retry_transient(op: str, key: str, fn, *, attempts: int = 4,
                    base: float = 0.1, cap: float = 2.0, seed: int = 0,
                    sleep=None):
    """Run ``fn`` retrying transient HTTP failures with the shared
    seeded-jitter backoff (``volcano_store_write_retries_total`` per
    retry). Non-transient errors raise immediately; exhausting the
    budget logs loudly WITH the object key — a write the caller thought
    landed silently vanishing is the failure mode this exists to kill.

    At-least-once caveat: a write that COMMITTED server-side but whose
    response was lost (connection reset after commit) is replayed, and
    the replay surfaces as the semantic verdict of a duplicate — 409
    (create: already exists / update: stale resource_version). That is
    the conflict path every caller already handles with a re-get+retry
    round trip (the store's normal optimistic-concurrency contract), so
    the lost-response success degrades to one extra conflict loop, never
    to a silent loss or a silent double-apply."""
    import time as _time
    sleep = sleep if sleep is not None else _time.sleep
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            attempt += 1
            if not _is_transient(e) or attempt >= attempts:
                if _is_transient(e):
                    log.error("store write %s %s failed after %d "
                              "attempt(s): %s", op, key, attempt, e)
                raise
            try:
                from ..metrics import metrics as _m
                _m.inc(_m.STORE_WRITE_RETRIES)
            except Exception:
                pass
            delay = seeded_backoff(f"{op}:{key}", attempt, base, cap,
                                   seed=seed)
            # a throttled write (429) carries the server's own horizon:
            # honor it as the floor — retrying earlier is a guaranteed
            # second rejection that only burns the tenant's bucket
            retry_after = getattr(e, "retry_after", None)
            if retry_after:
                delay = max(delay, float(retry_after))
            log.warning("store write %s %s failed (%s); retry %d/%d in "
                        "%.3fs", op, key, e, attempt, attempts - 1, delay)
            sleep(delay)


class RemoteStore:
    """ObjectStore-compatible facade over a remote apiserver process."""

    # write-path retry budget for transient HTTP errors (a blip used to
    # raise straight through to the caller)
    WRITE_ATTEMPTS = 4
    WRITE_BACKOFF_BASE_S = 0.1
    WRITE_BACKOFF_CAP_S = 2.0
    # watch reconnect backoff: consecutive poll failures back off
    # exponentially instead of hammering a down server at 1 Hz forever
    WATCH_BACKOFF_BASE_S = 0.5
    WATCH_BACKOFF_CAP_S = 15.0

    def __init__(self, base_url, poll_timeout: float = 25.0):
        # ``base_url``: one endpoint, or a list of replica endpoints.
        # With a list, writes route to the fenced leader (StoreClient's
        # /leader discovery + 503/412 handling) while the WATCH stream
        # fails over independently — any replica serves watches, so a
        # broken stream migrates to the next endpoint and resumes from
        # the mirror's cursor (the prev-chain/relist contract; replays
        # dedup on server rv).
        self.client = StoreClient(base_url)
        self.endpoints = list(self.client.endpoints)
        self.base_url = self.endpoints[0]
        self._watch_url = self.endpoints[0]
        self.watch_failovers = 0
        self.mirror = ObjectStore()
        self.poll_timeout = poll_timeout
        self._rv = 0
        # read-your-writes: a component must observe its own successful
        # writes immediately (the in-process store's synchronous watches
        # gave controllers exactly that; without it, get+mutate+update
        # round trips conflict against the component's own lagging
        # mirror). Successful writes self-apply to the mirror; the poll
        # stream's redeliveries are deduped by server resource_version.
        self._seen: dict = {}
        self._seen_lock = threading.Lock()
        # correlation IDs observed on the watch stream (the server echoes
        # a write's ?trace= back as the journal event's "trace" field),
        # keyed by SERVER rv — the same join key trace_of uses on the
        # in-process store. Bounded: old entries age out with the deque.
        from collections import deque as _deque
        self._trace_events: _deque = _deque(maxlen=4096)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.watch_restarts = 0
        # explicit cursor-gap relists (the structured "gone" contract,
        # docs/design/serving.md): counted apart from restart backoff —
        # a gap is a re-anchor, not a failure
        self.watch_relists = 0
        # streaming transport (/watchstream): preferred; a 404 from a
        # pre-serving server downgrades to the long-poll /watch forever
        self._use_stream = True
        import os as _os
        self._client_id = f"remote-{_os.getpid()}-{id(self):x}"
        self._resync()
        self.events = self.mirror.events   # local event record view

    # -- sync loop ---------------------------------------------------------

    def _resync(self) -> None:
        """Prime (or re-prime) the mirror with a full list per kind.

        List+watch anchoring: the SERVER's current rv is read FIRST, the
        lists reflect state at or after it, and the poll resumes from that
        anchor — replayed events older than a listed object's server rv
        are skipped by the _seen dedup (the mirror stamps its own local
        rvs, which must never be confused with the server's)."""
        try:
            resp = json.loads(urllib.request.urlopen(
                f"{self.client.base_url}/rv", timeout=10.0).read().decode())
            anchor = int(resp.get("rv", 0))
        except Exception:
            log.exception("rv anchor fetch failed during resync")
            anchor = self._rv
        for kind in KINDS:
            try:
                remote = {self.mirror.key_of(kind, o): o
                          for o in self.client.list(kind)}
            except Exception:
                log.exception("list %s failed during resync", kind)
                continue
            with self.mirror._lock:
                local_keys = set(self.mirror._objects[kind])
            for key in local_keys - set(remote):
                ns, _, name = key.rpartition("/")
                with self._seen_lock:
                    self._seen[(kind, key)] = max(
                        self._seen.get((kind, key), 0), anchor)
                try:
                    self.mirror.delete(kind, name, ns or "default",
                                       skip_admission=True)
                except KeyError:
                    pass
            for key, o in remote.items():
                self._apply("MODIFIED" if key in local_keys else "ADDED",
                            kind, o, o.metadata.resource_version)
        self._rv = max(self._rv, anchor)

    def _apply(self, action: str, kind: str, o, rv: int = 0) -> None:
        key = self.mirror.key_of(kind, o)
        with self._seen_lock:
            if rv and self._seen.get((kind, key), 0) >= rv:
                return   # already applied (self-write or newer event)
            if rv:
                self._seen[(kind, key)] = rv
        if action == "DELETED":
            try:
                self.mirror.delete(kind, o.metadata.name,
                                   o.metadata.namespace, skip_admission=True)
            except KeyError:
                pass
            return
        with self.mirror._lock:
            exists = key in self.mirror._objects[kind]
        try:
            if exists:
                o.metadata.resource_version = 0   # mirror manages its own rv
                self.mirror.update(kind, o, skip_admission=True)
            else:
                self.mirror.create(kind, o, skip_admission=True)
        except KeyError:
            log.exception("mirror apply %s %s failed", action, kind)

    def _relist(self, anchor_rv: Optional[int] = None) -> None:
        """The structured cursor-gap path (docs/design/serving.md): the
        server said ``gone``/``relist`` — the cursor fell off the
        journal window — so re-list everything and re-anchor, explicitly
        and immediately, instead of burning a restart-backoff cycle on
        what is not a failure."""
        self.watch_relists += 1
        try:
            from ..metrics import metrics as _m
            _m.inc(_m.WATCH_RELISTS)
        except Exception:
            pass
        self._resync()
        if anchor_rv is not None:
            self._rv = max(self._rv, int(anchor_rv))

    def _apply_wire_event(self, ev: dict) -> None:
        o = decode_object(ev["kind"], ev["object"])
        if ev.get("trace") is not None:
            with self._seen_lock:
                self._trace_events.append((int(ev["rv"]), ev["trace"]))
        self._apply(ev["action"], ev["kind"], o, int(ev["rv"]))
        self._rv = max(self._rv, int(ev["rv"]))

    def _poll_once(self) -> None:
        """One long-poll round against /watch (the pre-serving
        transport, kept as the fallback)."""
        url = (f"{self._watch_url}/watch?since={self._rv}"
               f"&timeout={self.poll_timeout}")
        with urllib.request.urlopen(
                url, timeout=self.poll_timeout + 10.0) as resp:
            data = json.loads(resp.read().decode())
        if data.get("gone") or data.get("resync"):
            self._relist(data.get("rv"))
            return
        for ev in data.get("events", []):
            self._apply_wire_event(ev)

    def _stream_once(self) -> None:
        """One /watchstream session: hold the chunked connection and
        apply coalesced frames as the hub publishes them. Returns on a
        relist (after re-anchoring — the caller restarts the stream
        from the fresh cursor); raises on any transport failure (the
        caller's seeded-backoff restart, same as the long-poll)."""
        import http.client
        u = urllib.parse.urlsplit(self._watch_url)
        conn = http.client.HTTPConnection(
            u.hostname or "127.0.0.1", u.port or 80,
            timeout=self.poll_timeout + 10.0)
        try:
            hb = max(1.0, min(self.poll_timeout, 10.0))
            conn.request(
                "GET",
                f"/watchstream?cursor={self._rv}&heartbeat={hb}"
                f"&client={urllib.parse.quote(self._client_id)}")
            resp = conn.getresponse()
            if resp.status == 404:
                resp.read()
                raise _StreamUnsupported()
            if resp.status != 200:
                raise ApiError(resp.status,
                               f"watchstream HTTP {resp.status}")
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    raise ConnectionError("watch stream closed")
                frame = json.loads(line)
                if frame.get("ping") or frame.get("hello"):
                    continue
                if frame.get("relist"):
                    self._relist(frame.get("rv"))
                    return   # restart the stream from the fresh anchor
                for ev in frame.get("events", []):
                    self._apply_wire_event(ev)
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _poll_loop(self) -> None:
        """Keep the mirror current forever — streaming /watchstream
        when the server offers it (one held connection, frames pushed
        as they publish), long-poll /watch otherwise. EVERY failure
        mode — a dead server, a poisoned event payload, a resync that
        itself fails — restarts the stream with capped seeded
        exponential backoff (``volcano_watch_restarts_total``) instead
        of killing the thread: a watch thread dying silently leaves the
        mirror frozen at a stale rv with nothing ever noticing (the
        pre-failover behavior). A cursor GAP is not a failure: the
        structured gone/relist signal takes the explicit re-anchor path
        (``volcano_watch_relists_total``) with no backoff."""
        failures = 0
        while not self._stop.is_set():
            try:
                if self._use_stream:
                    self._stream_once()
                else:
                    self._poll_once()
            except _StreamUnsupported:
                log.info("server has no /watchstream; long-polling")
                self._use_stream = False
                continue
            except Exception:
                if self._stop.is_set():
                    return
                failures += 1
                self.watch_restarts += 1
                try:
                    from ..metrics import metrics as _m
                    _m.inc(_m.WATCH_RESTARTS)
                except Exception:
                    pass
                if len(self.endpoints) > 1:
                    # replica failover: any replica serves watches —
                    # resume from the mirror's cursor on the next
                    # endpoint (server-rv dedup absorbs replays, the
                    # relist contract covers a rolled-past cursor)
                    i = self.endpoints.index(self._watch_url)
                    self._watch_url = self.endpoints[
                        (i + 1) % len(self.endpoints)]
                    self.watch_failovers += 1
                delay = seeded_backoff(self._watch_url, failures,
                                       self.WATCH_BACKOFF_BASE_S,
                                       self.WATCH_BACKOFF_CAP_S)
                log.warning("watch poll failed (failure %d); restarting "
                            "the stream on %s in %.2fs", failures,
                            self._watch_url, delay, exc_info=True)
                self._stop.wait(delay)
                continue
            failures = 0   # a clean round closes the backoff window

    def run(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="remote-store-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- ObjectStore interface ---------------------------------------------

    key_of = staticmethod(ObjectStore.key_of)

    @property
    def clock(self):
        return self.mirror.clock

    @staticmethod
    def _map_error(e):
        """HTTP status -> the in-process store's exception types, so
        controllers' retry/conflict handling works unchanged."""
        from .http import ApiError
        from .store import ConflictError, FencedError
        if isinstance(e, ApiError):
            if e.code == 409 and "resource_version" in e.message:
                return ConflictError(e.message)
            if e.code in (404, 409):
                return KeyError(e.message)
            if e.code == 412:
                return FencedError(e.message)
            if e.code == 422:
                return AdmissionError(e.message)
        return e

    def _retrying(self, op: str, key: str, fn):
        return retry_transient(op, key, fn, attempts=self.WRITE_ATTEMPTS,
                               base=self.WRITE_BACKOFF_BASE_S,
                               cap=self.WRITE_BACKOFF_CAP_S)

    def advance_fence(self, token: int) -> int:
        """Announce a freshly-acquired fencing token to the serving
        process (LeaderElector duck-types this against both stores)."""
        return self._retrying("advance_fence", str(token),
                              lambda: self.client.advance_fence(token))

    def trace_of(self, server_rv: int):
        """Correlation ID the watch stream delivered for ``server_rv``
        (the remote twin of ``ObjectStore.trace_of``; None when the event
        was unstamped, aged out, or not yet polled)."""
        with self._seen_lock:
            events = list(self._trace_events)
        for rv, trace in reversed(events):
            if rv == server_rv:
                return trace
        return None

    def create(self, kind: str, o, skip_admission: bool = False,
               fence: Optional[int] = None, trace: Optional[str] = None):
        try:
            created = self._retrying(
                "create", f"{kind}/{self.key_of(kind, o)}",
                lambda: self.client.create(kind, o, fence=fence,
                                           trace=trace))
        except Exception as e:
            raise self._map_error(e) from None
        # the in-process store stamps uid/rv on the caller's object in
        # place; callers chain writes on the same object, so mirror that
        # contract (otherwise the very next update conflicts on rv)
        o.metadata.uid = created.metadata.uid
        o.metadata.creation_timestamp = created.metadata.creation_timestamp
        o.metadata.resource_version = created.metadata.resource_version
        # the mirror gets its own copy: _apply restamps mirror-local rvs
        # and retains the instance, and the caller's returned object must
        # keep the authoritative server rv untouched
        self._apply("ADDED", kind, fast_clone(created),
                    created.metadata.resource_version)
        return created

    def update(self, kind: str, o, skip_admission: bool = False,
               fence: Optional[int] = None, trace: Optional[str] = None):
        try:
            updated = self._retrying(
                "update", f"{kind}/{self.key_of(kind, o)}",
                lambda: self.client.update(kind, o, fence=fence,
                                           trace=trace))
        except Exception as e:
            raise self._map_error(e) from None
        o.metadata.resource_version = updated.metadata.resource_version
        self._apply("MODIFIED", kind, fast_clone(updated),
                    updated.metadata.resource_version)
        return updated

    def delete(self, kind: str, name: str, namespace: str = "default",
               skip_admission: bool = False, fence: Optional[int] = None,
               trace: Optional[str] = None):
        try:
            resp = self._retrying(
                "delete", f"{kind}/{namespace}/{name}",
                lambda: self.client.delete(kind, name, namespace,
                                           fence=fence, trace=trace))
        except Exception as e:
            raise self._map_error(e) from None
        rv = int((resp or {}).get("rv", 0)) if isinstance(resp, dict) else 0
        with self._seen_lock:
            if rv:
                key = name if kind in _CLUSTER_SCOPED else                     f"{namespace}/{name}"
                self._seen[(kind, key)] = rv
        try:
            self.mirror.delete(kind, name, namespace, skip_admission=True)
        except KeyError:
            pass

    def get(self, kind: str, name: str, namespace: str = "default"):
        # reads go to the source of truth: controllers do get+mutate+update
        # round trips that need the live resource_version
        return self.client.get(kind, name, namespace)

    def list(self, kind: str, namespace=None) -> list:
        return self.client.list(kind, namespace)

    # read-path offload (docs/design/serving.md): monitoring/read-heavy
    # consumers can serve from the watch-maintained, anti-entropy-
    # repaired mirror without an HTTP round trip or a per-object clone
    # (the list_refs no-copy contract: refs are consistent views, MUST
    # NOT be mutated). Mirror resource_versions are MIRROR-LOCAL — a
    # get+mutate+update round trip needs list()/get() for the server rv.

    def list_cached(self, kind: str, namespace=None) -> list:
        return self.mirror.list_refs(kind, namespace)

    def get_cached(self, kind: str, name: str,
                   namespace: str = "default"):
        return self.mirror.get_ref(kind, name, namespace)

    def watch(self, kind: str, on_add=None, on_update=None, on_delete=None,
              filter_fn=None, sync: bool = True, on_bulk_update=None):
        # bulk delivery is an in-process fast path; the remote mirror
        # replays journal events one at a time, so bulk subscribers simply
        # receive per-event on_update calls (same semantics)
        return self.mirror.watch(kind, on_add, on_update, on_delete,
                                 filter_fn, sync)

    def unwatch(self, w) -> None:
        self.mirror.unwatch(w)

    def register_admission(self, hook) -> None:
        raise NotImplementedError(
            "admission hooks register on the serving process; run a "
            "webhook-manager with --server to register remotely")

    def record_event(self, kind: str, o, event_type: str, reason: str,
                     message: str) -> None:
        payload = {"kind": kind,
                   "object": encode_object(kind, o) if o is not None else None,
                   "event_type": event_type, "reason": reason,
                   "message": message}
        req = urllib.request.Request(
            f"{self.client.base_url}/events",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=10.0).close()
        except Exception:
            log.warning("event record failed", exc_info=True)
