"""JSON codec for API objects: dataclass <-> plain-dict conversion.

The reference's objects serialize through k8s apimachinery; here a generic
reflection codec covers every kind so the HTTP layer (http.py), the CLI and
state persistence share one wire format. bytes fields (Secret data) are
base64-encoded; nested dataclasses/lists/dicts/Optionals are handled from
the type hints.
"""

from __future__ import annotations

import base64
import dataclasses
import typing
from typing import Any, Dict, Optional

from ..models import objects as obj

# kind -> dataclass (the store's KINDS each map to one root type)
KIND_TYPES: Dict[str, type] = {
    "pods": obj.Pod,
    "nodes": obj.Node,
    "podgroups": obj.PodGroup,
    "queues": obj.Queue,
    "jobs": obj.Job,
    "commands": obj.Command,
    "priorityclasses": obj.PriorityClass,
    "resourcequotas": obj.ResourceQuota,
    "numatopologies": obj.Numatopology,
    "services": obj.Service,
    "configmaps": obj.ConfigMap,
    "secrets": obj.Secret,
    "networkpolicies": obj.NetworkPolicy,
    "persistentvolumeclaims": obj.PersistentVolumeClaim,
    "persistentvolumes": obj.PersistentVolume,
}


def encode(o: Any) -> Any:
    """Dataclass instance -> JSON-compatible structure."""
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return {f.name: encode(getattr(o, f.name))
                for f in dataclasses.fields(o)}
    if isinstance(o, dict):
        return {str(k): encode(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [encode(v) for v in o]
    if isinstance(o, bytes):
        return {"__bytes__": base64.b64encode(o).decode("ascii")}
    return o


def _resolve(tp):
    """Unwrap Optional[X] to X; return (origin, args) for generics."""
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is typing.Union:
        non_none = [a for a in args if a is not type(None)]
        if len(non_none) == 1:
            return _resolve(non_none[0])
    return tp, origin, args


_HINT_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    if cls not in _HINT_CACHE:
        _HINT_CACHE[cls] = typing.get_type_hints(cls)
    return _HINT_CACHE[cls]


def decode(data: Any, tp: Any) -> Any:
    """JSON structure -> instance of tp (driven by dataclass type hints)."""
    if data is None:
        return None
    if isinstance(data, dict) and "__bytes__" in data and len(data) == 1:
        return base64.b64decode(data["__bytes__"])
    tp, origin, args = _resolve(tp)
    if dataclasses.is_dataclass(tp):
        hints = _hints(tp)
        kwargs = {}
        for f in dataclasses.fields(tp):
            if f.name in data:
                kwargs[f.name] = decode(data[f.name], hints[f.name])
        return tp(**kwargs)
    if origin in (list, tuple):
        elem = args[0] if args else Any
        return [decode(v, elem) for v in data]
    if origin is dict:
        key_tp = args[0] if args else str
        val_tp = args[1] if len(args) > 1 else Any
        out = {}
        for k, v in data.items():
            if key_tp is int:
                k = int(k)
            out[k] = decode(v, val_tp)
        return out
    return data


def encode_object(kind: str, o: Any) -> Dict[str, Any]:
    return encode(o)


def decode_object(kind: str, data: Dict[str, Any]) -> Any:
    cls = KIND_TYPES.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}")
    return decode(data, cls)
