"""vcctl: the framework CLI (reference: cmd/cli/vcctl.go).

    vcctl job   {run,list,view,suspend,resume,delete}
    vcctl queue {create,list,get,delete,operate}
    vcctl sim   {run,smoke,chaos,failover,obs,replay}
    vcctl debug {cycles,pending,health,latency,timeseries}

job/queue talk HTTP to a running control plane (python -m
volcano_tpu.cmd.cluster); --server or $VOLCANO_SERVER selects the
endpoint. sim needs no server: the churn simulator owns its whole
control plane in-process. debug talks to the scheduler's METRICS
server (--metrics / $VOLCANO_METRICS) and pretty-prints its /debug/*
endpoints.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import job as job_cmds
from . import queue as queue_cmds
from .util import DEFAULT_SERVER, get_client


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="vcctl", description="volcano-tpu command line client")
    parser.add_argument("--server", "-s", default=DEFAULT_SERVER,
                        help="control plane endpoint")
    sub = parser.add_subparsers(dest="group", required=True)

    sub.add_parser("version", help="print client version")

    job = sub.add_parser("job", help="job operations").add_subparsers(
        dest="verb", required=True)

    run = job.add_parser("run", help="create a job")
    run.add_argument("--name", "-N", default="")
    run.add_argument("--namespace", "-n", default="default")
    run.add_argument("--image", "-i", default="busybox")
    run.add_argument("--min", "-m", type=int, default=1, dest="min_available")
    run.add_argument("--replicas", "-r", type=int, default=1)
    run.add_argument("--requests", "-R", default="cpu=1000m,memory=100Mi")
    run.add_argument("--limits", "-L", default="cpu=1000m,memory=100Mi")
    run.add_argument("--scheduler", "-S", default="volcano")
    run.add_argument("--queue", "-q", default="default")
    run.add_argument("--filename", "-f", default=None)

    ls = job.add_parser("list", help="list jobs")
    ls.add_argument("--namespace", "-n", default="default")
    ls.add_argument("--all-namespaces", action="store_true")
    ls.add_argument("--scheduler", "-S", default="")
    ls.add_argument("--selector", default="")

    for verb in ("view", "suspend", "resume", "delete"):
        p = job.add_parser(verb, help=f"{verb} a job")
        p.add_argument("--name", "-N", default="")
        p.add_argument("--namespace", "-n", default="default")

    queue = sub.add_parser("queue", help="queue operations").add_subparsers(
        dest="verb", required=True)

    qc = queue.add_parser("create", help="create a queue")
    qc.add_argument("--name", "-n", default="")
    qc.add_argument("--weight", "-w", type=int, default=1)
    qc.add_argument("--capability", "-c", default="")

    queue.add_parser("list", help="list queues")
    for verb in ("get", "delete"):
        p = queue.add_parser(verb, help=f"{verb} a queue")
        p.add_argument("--name", "-n", default="")

    qo = queue.add_parser("operate", help="open/close/update a queue")
    qo.add_argument("--name", "-n", default="")
    qo.add_argument("--action", "-a", default="",
                    help="open | close | update")
    qo.add_argument("--weight", "-w", type=int, default=0)

    from ..sim.cli import add_sim_parser
    add_sim_parser(sub)

    from .debug import add_debug_parser
    add_debug_parser(sub)

    return parser


def dispatch(args, client=None) -> str:
    if args.group == "version":
        from ..version import version_string
        return version_string()
    client = client if client is not None else get_client(args.server)
    if args.group == "job":
        if args.verb == "run":
            return job_cmds.run_job(
                client, args.name, args.namespace, args.image, args.replicas,
                args.min_available, args.requests, args.limits, args.scheduler,
                args.queue, args.filename)
        if args.verb == "list":
            return job_cmds.list_jobs(client, args.namespace,
                                      args.all_namespaces, args.scheduler,
                                      args.selector)
        if args.verb == "view":
            return job_cmds.view_job(client, args.name, args.namespace)
        if args.verb == "suspend":
            return job_cmds.suspend_job(client, args.name, args.namespace)
        if args.verb == "resume":
            return job_cmds.resume_job(client, args.name, args.namespace)
        if args.verb == "delete":
            return job_cmds.delete_job(client, args.name, args.namespace)
    if args.group == "queue":
        if args.verb == "create":
            return queue_cmds.create_queue(client, args.name, args.weight,
                                           args.capability)
        if args.verb == "list":
            return queue_cmds.list_queues(client)
        if args.verb == "get":
            return queue_cmds.get_queue(client, args.name)
        if args.verb == "delete":
            return queue_cmds.delete_queue(client, args.name)
        if args.verb == "operate":
            return queue_cmds.operate_queue(client, args.name, args.action,
                                            args.weight)
    raise ValueError(f"unknown command {args.group} {args.verb}")


def main(argv: Optional[List[str]] = None, client=None) -> int:
    args = build_parser().parse_args(argv)
    if args.group == "sim":
        # serverless: the simulator prints its own summary and returns an
        # exit code (nonzero on invariant violations / smoke failure)
        from ..sim.cli import dispatch_sim
        try:
            return dispatch_sim(args)
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    if args.group == "debug":
        # talks to the metrics server, not the apiserver client
        from .debug import dispatch_debug
        try:
            return dispatch_debug(args)
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    try:
        print(dispatch(args, client))
        return 0
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
