"""`vcctl debug`: fetch and pretty-print a running scheduler's /debug/*.

    vcctl debug cycles          last N traced cycles (seq, wall, phases)
    vcctl debug pending         why-pending per job / per reason
    vcctl debug health          component health (exit 1 while degraded)
    vcctl debug latency         pod lifecycle ledger percentiles
    vcctl debug timeseries      last N cycles of key gauges/counters
    vcctl debug explain [job]   placement decision provenance (one job's
                                record, or the newest records + the
                                pruning-readiness aggregates)
    vcctl debug replication     replica-set state: epoch, follower lag /
                                applied rvs, gap/bootstrap/fence counters,
                                last anti-entropy audit
    vcctl debug durability      write-ahead-log state: durable rv / lag,
                                fsync latency, segments, last recovery
                                (exit 1 while the store is read-only)

Talks to the metrics server (`--metrics` / $VOLCANO_METRICS, default
http://127.0.0.1:8080), not the apiserver; `--json` prints the raw
payload for piping into jq.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import List

DEFAULT_METRICS = os.environ.get("VOLCANO_METRICS",
                                 "http://127.0.0.1:8080")
VERBS = ("cycles", "pending", "health", "latency", "timeseries",
         "explain", "replication", "durability")


def fetch(server: str, path: str, timeout: float = 10.0):
    """(status, payload) for one /debug GET; non-2xx still parses the
    JSON error body (health serves 503 while degraded by design)."""
    url = server.rstrip("/") + path
    if not url.startswith("http"):
        url = "http://" + url
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except Exception:
            return e.code, {"error": str(e)}


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(lines)


def _render_cycles(payload: dict) -> str:
    cycles = payload.get("cycles", [])
    if not cycles:
        return ("no traced cycles (tracer "
                f"{'enabled' if payload.get('enabled') else 'DISABLED'})")
    rows = []
    for c in cycles[-20:]:
        top = sorted(c.get("phases", {}).items(),
                     key=lambda kv: -kv[1]["ms"])[:3]
        tags = c.get("tags") or {}
        mode = tags.get("mode", "-")
        if tags.get("quiet"):
            mode = f"{mode}*"      # * = quiet fast path taken
        dirty = f"{tags.get('dirty_jobs', '-')}/" \
                f"{tags.get('dirty_nodes', '-')}" \
            if "dirty_jobs" in tags else "-"
        rows.append([c["seq"], c["cycle_ms"],
                     f"{c.get('coverage', 0):.2f}",
                     mode, dirty, tags.get("skipped_tasks", "-"),
                     c.get("bind_flush_ms", ""),
                     ",".join(c.get("over_budget", [])) or "-",
                     " ".join(f"{n}={e['ms']}" for n, e in top)])
    return _table(rows, ["seq", "cycle_ms", "cover", "mode", "dirty j/n",
                         "skipped", "flush_ms", "over_budget",
                         "top phases (ms)"])


def _render_pending(payload: dict) -> str:
    lines = [f"pending jobs: {payload.get('pending_jobs', 0)}"]
    if payload.get("idle_reason"):
        lines.append(f"idle: {payload['idle_reason']} "
                     f"({payload.get('detail', '')})")
    reasons = payload.get("reasons") or {}
    if reasons:
        lines.append(_table(
            [[r, n] for r, n in sorted(reasons.items(),
                                       key=lambda kv: -kv[1])],
            ["reason", "tasks"]))
    jobs = payload.get("jobs") or {}
    if jobs:
        rows = [[k, j["queue"], j["pending_tasks"], j["unready"],
                 j["min_available"],
                 "; ".join(f"{r} x{n}" for r, n in j["reasons"].items())]
                for k, j in sorted(jobs.items())]
        lines.append(_table(rows, ["job", "queue", "pending", "unready",
                                   "min", "reasons"]))
    return "\n".join(lines)


def _render_health(payload: dict) -> str:
    lines = [f"healthy: {payload.get('healthy')}"]
    comps = payload.get("components") or {}
    if comps:
        rows = [[name, c["healthy"], c.get("detail", "")]
                for name, c in sorted(comps.items())]
        lines.append(_table(rows, ["component", "healthy", "detail"]))
    return "\n".join(lines)


def _render_latency(payload: dict) -> str:
    lines = [f"ledger: enabled={payload.get('enabled')} "
             f"open={payload.get('open')} "
             f"completed={payload.get('completed')} "
             f"dropped={payload.get('dropped')} "
             f"detours={payload.get('detours')}"]
    hops = payload.get("hops") or {}
    if hops:
        rows = [[h, a["count"], a["mean_ms"], a["p50"], a["p95"], a["p99"]]
                for h, a in hops.items()]
        lines.append(_table(rows, ["hop", "count", "mean_ms", "p50",
                                   "p95", "p99"]))
    per_q = payload.get("per_queue_e2e") or {}
    if per_q:
        rows = [[q or "(unknown)", a["count"], a["p50"], a["p95"],
                 a["p99"]] for q, a in per_q.items()]
        lines.append("per-queue e2e:")
        lines.append(_table(rows, ["queue", "count", "p50", "p95", "p99"]))
    recent = payload.get("recent") or []
    if recent:
        rows = [[r["pod"], r.get("trace") or "-", r["e2e_ms"]]
                for r in recent[-10:]]
        lines.append("recent completions:")
        lines.append(_table(rows, ["pod", "trace", "e2e_ms"]))
    return "\n".join(lines)


def _render_timeseries(payload: dict) -> str:
    samples = payload.get("samples") or []
    if not samples:
        return "no samples (tracer off, or no cycle has run)"
    cols: List[str] = []
    for s in samples:
        for k in s:
            if k not in cols:
                cols.append(k)
    short = {c: c.replace("volcano_", "") for c in cols}
    rows = [[s.get(c, "") for c in cols] for s in samples[-15:]]
    return _table(rows, [short[c] for c in cols])


def _fmt_elims(elims: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(elims.items())) or "-"


def _render_explain(payload: dict) -> str:
    if "error" in payload:     # structured 404 (unknown job)
        return (f"{payload['error']} (explainer "
                f"{'enabled' if payload.get('enabled') else 'DISABLED'})")
    if "groups" in payload:    # single-job record (?job=)
        lines = [f"job {payload.get('job')}  cycle={payload.get('cycle')} "
                 f"kernel={payload.get('kernel')} "
                 f"queue={payload.get('queue')} "
                 f"committed={payload.get('committed')}"]
        for g in payload.get("groups", []):
            lines.append(
                f"  gang {g['gang']}: placed {g['placed']}/{g['tasks']} "
                f"winner={g['winner']} feasible={g['feasible']}/"
                f"{g['nodes']} margin={g['win_margin']}")
            lines.append(f"    eliminations: "
                         f"{_fmt_elims(g.get('eliminations', {}))}")
            lines.append("    coverage: " + " ".join(
                f"k={k}:{v}" for k, v in sorted(
                    g.get("coverage", {}).items(), key=lambda kv:
                    int(kv[0]))))
            for e in g.get("topk", [])[:8]:
                terms = " ".join(f"{k}={v}" for k, v in
                                 sorted(e.get("terms", {}).items()))
                lines.append(f"    cand {e['node']} score={e['score']} "
                             f"{terms}")
        return "\n".join(lines)
    lines = [f"explain: enabled={payload.get('enabled')} "
             f"records={payload.get('records')} "
             f"fingerprint={str(payload.get('fingerprint'))[:16]}…"]
    agg = payload.get("aggregates") or {}
    feas = agg.get("feasible_nodes") or {}
    if feas.get("count"):
        lines.append(f"feasible nodes/gang: n={feas['count']} "
                     f"p50={feas.get('p50')} p90={feas.get('p90')} "
                     f"p99={feas.get('p99')} mean={feas.get('mean')}")
    cov = agg.get("topk_coverage") or {}
    if cov:
        lines.append("top-k score coverage: " + " ".join(
            f"k={k}:{v}" for k, v in sorted(cov.items(),
                                            key=lambda kv: int(kv[0]))))
    if agg.get("fragmentation_ratio") is not None:
        lines.append(f"fragmentation ratio: "
                     f"{agg['fragmentation_ratio']}")
    jobs = payload.get("jobs") or {}
    if jobs:
        rows = []
        for key, rec in list(jobs.items())[-20:]:
            g = (rec.get("groups") or [{}])[0]
            rows.append([key, rec.get("kernel"), g.get("winner"),
                         f"{g.get('feasible')}/{g.get('nodes')}",
                         g.get("win_margin"),
                         _fmt_elims(g.get("eliminations", {}))])
        lines.append(_table(rows, ["job", "kernel", "winner",
                                   "feasible", "margin",
                                   "eliminations"]))
    victims = payload.get("victims") or []
    if victims:
        rows = [[v["preemptor"], v["mode"], v["node"],
                 v.get("winning_tier"), len(v.get("victims", [])),
                 v.get("candidates")] for v in victims[-10:]]
        lines.append("victim decisions:")
        lines.append(_table(rows, ["preemptor", "mode", "node", "tier",
                                   "victims", "candidates"]))
    return "\n".join(lines)


def _render_replication(payload: dict) -> str:
    lines_pre = []
    m = payload.get("member")
    if m:   # elector-driven federation process mode
        lines_pre.append(
            f"member {m.get('name')}: role={m.get('role')} "
            f"lease={m.get('lease_holder') or '-'}"
            f"@{m.get('lease_token')} token={m.get('token')} "
            f"takeovers={m.get('takeovers')} "
            f"demotions={m.get('demotions')} "
            f"accepts_writes={m.get('accepts_writes')}")
    f = payload.get("follower")
    if f:   # this process IS a follower apiserver replica
        return "\n".join(lines_pre + [
            f"follower {f['name']}: epoch={f['epoch']} "
            f"applied_rv={f['applied_rv']} lag={f.get('lag_rvs')} "
            f"frames={f['frames_applied']} gaps={f['gaps_detected']} "
            f"catchup={f['catchup_relists']} "
            f"bootstraps={f['snapshot_bootstraps']} "
            f"fenced={f['fenced_frames']}"])
    rs = payload.get("replica_set")
    if not rs:
        return "\n".join(lines_pre) if lines_pre else \
            "no replica set registered (single-replica deployment)"
    leader = rs.get("leader") or {}
    lines = lines_pre + [
        f"epoch: {rs.get('epoch')}  leader rv={leader.get('rv')} "
             f"frames_shipped={leader.get('frames_shipped')} "
             f"events_shipped={leader.get('events_shipped')} "
             f"snapshots_shipped={leader.get('snapshots_shipped')}"]
    lag = rs.get("lag_rvs") or {}
    followers = rs.get("followers") or []
    if followers:
        rows = [[f["name"], f["epoch"], f["applied_rv"],
                 lag.get(f["name"], "-"), f["frames_applied"],
                 f["gaps_detected"], f["catchup_relists"],
                 f["snapshot_bootstraps"], f["fenced_frames"]]
                for f in followers]
        lines.append(_table(rows, ["follower", "epoch", "applied_rv",
                                   "lag", "frames", "gaps", "catchup",
                                   "bootstraps", "fenced"]))
    if rs.get("dead"):
        lines.append(f"dead: {', '.join(rs['dead'])}")
    lines.append(f"cursor handoffs: {rs.get('cursor_handoffs', 0)}")
    audit = rs.get("last_audit")
    if audit:
        lines.append(f"last audit: {audit['verdict']} "
                     f"@ leader rv {audit['leader_rv']}"
                     + (f" divergent: {', '.join(audit['divergent'])}"
                        if audit.get("divergent") else ""))
    else:
        lines.append("last audit: (none run)")
    return "\n".join(lines)


def _render_durability(payload: dict) -> str:
    if not payload.get("attached"):
        lines = ["no WAL attached (started without --data-dir)"]
    else:
        lines = [
            f"wal {payload.get('data_dir')}: gen={payload.get('generation')} "
            f"durable_rv={payload.get('durable_rv')} "
            f"store_rv={payload.get('store_rv')} "
            f"lag={payload.get('lag_entries')} entries",
            _table([[payload.get("segments"), payload.get("segment_bytes"),
                     payload.get("records_written"),
                     payload.get("entries_written"),
                     payload.get("fsyncs"),
                     payload.get("fsync_p50_ms"),
                     payload.get("fsync_p99_ms"),
                     payload.get("append_p99_ms"),
                     payload.get("compactions"),
                     payload.get("rotations")]],
                   ["segs", "bytes", "records", "entries", "fsyncs",
                    "fsync_p50", "fsync_p99", "append_p99", "compact",
                    "rotate"]),
        ]
        if payload.get("read_only"):
            lines.append(f"READ-ONLY: {payload.get('degraded_reason')} "
                         "(writes 503 + Retry-After until the append "
                         "path heals)")
    rec = payload.get("last_recovery")
    if rec:
        lines.append(
            f"last recovery: rv {rec.get('snapshot_rv')} -> "
            f"{rec.get('final_rv')} "
            f"({rec.get('snapshot_objects')} snapshot objects, "
            f"{rec.get('entries_replayed')} WAL entries, "
            f"{rec.get('torn_records_truncated')} torn records truncated, "
            f"{rec.get('recovery_ms')}ms)")
    return "\n".join(lines)


_RENDER = {"cycles": _render_cycles, "pending": _render_pending,
           "health": _render_health, "latency": _render_latency,
           "timeseries": _render_timeseries, "explain": _render_explain,
           "replication": _render_replication,
           "durability": _render_durability}


def _replication_degraded(payload: dict, max_lag: int):
    """The reason `vcctl debug replication` should exit nonzero, or
    None: follower lag past the threshold, a diverged last audit, or a
    member with no electable leader — the same exit-1-while-degraded
    convention `vcctl debug health` follows."""
    reasons = []
    rs = payload.get("replica_set") or {}
    for name, lag in sorted((rs.get("lag_rvs") or {}).items()):
        if lag > max_lag:
            reasons.append(f"follower {name} lag {lag} rvs "
                           f"> --max-lag {max_lag}")
    audit = rs.get("last_audit")
    if audit and audit.get("verdict") not in (None, "identical"):
        reasons.append(
            f"last audit {audit.get('verdict')}"
            + (f" (divergent: {', '.join(audit['divergent'])})"
               if audit.get("divergent") else ""))
    f = payload.get("follower")
    if f and (f.get("lag_rvs") or 0) > max_lag:
        reasons.append(f"follower {f.get('name')} lag "
                       f"{f.get('lag_rvs')} rvs > --max-lag {max_lag}")
    m = payload.get("member")
    if m and m.get("role") == "degraded":
        reasons.append(f"member {m.get('name')} degraded "
                       "(no electable leader)")
    return "; ".join(reasons) if reasons else None


def dispatch_debug(args) -> int:
    path = f"/debug/{args.verb}"
    if args.verb == "explain" and getattr(args, "job", None):
        path += "?job=" + urllib.parse.quote(args.job)
    status, payload = fetch(args.metrics, path)
    if args.json:
        print(json.dumps(payload, indent=1))
    else:
        print(_RENDER[args.verb](payload))
    # /debug/health 503s while degraded — the exit code should say so
    # (and an unknown-job explain lookup exits 1 the same way)
    if args.verb == "replication" and status < 400:
        reason = _replication_degraded(
            payload, getattr(args, "max_lag", 1000))
        if reason:
            print(f"DEGRADED: {reason}")
            return 1
    # a read-only store (ENOSPC/EIO degradation) is operationally
    # degraded even though the endpoint itself serves 200
    if args.verb == "durability" and status < 400 \
            and payload.get("read_only"):
        print(f"DEGRADED: store read-only "
              f"({payload.get('degraded_reason')})")
        return 1
    return 0 if status < 400 else 1


def add_debug_parser(sub) -> None:
    dbg = sub.add_parser(
        "debug", help="fetch and pretty-print a running scheduler's "
                      "/debug endpoints")
    dbg.add_argument("verb", choices=VERBS)
    dbg.add_argument("job", nargs="?", default=None,
                     help="explain only: one job's record (ns/name)")
    dbg.add_argument("--metrics", "-m", default=DEFAULT_METRICS,
                     help="metrics server endpoint "
                          "(default $VOLCANO_METRICS or "
                          "http://127.0.0.1:8080)")
    dbg.add_argument("--json", action="store_true",
                     help="print the raw JSON payload")
    dbg.add_argument("--max-lag", type=int, default=1000,
                     help="replication only: exit 1 when any follower "
                          "lags the leader by more than this many rvs "
                          "(default 1000)")
