"""CLI helpers (reference: pkg/cli/util + pkg/cli/job/util.go)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional

DEFAULT_SERVER = os.environ.get("VOLCANO_SERVER", "http://127.0.0.1:8181")


def get_client(server: Optional[str] = None):
    """A client speaking the store CRUD interface: remote HTTP by default;
    tests inject an in-process ObjectStore instead (same surface)."""
    from ..apiserver.http import StoreClient
    return StoreClient(server or DEFAULT_SERVER)


def parse_resource_list(spec: str) -> Dict[str, str]:
    """"cpu=1000m,memory=100Mi" -> {"cpu": "1000m", "memory": "100Mi"}
    (populateResourceListV1 equivalent)."""
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid resource spec {part!r}, want name=value")
        name, value = part.split("=", 1)
        out[name.strip()] = value.strip()
    return out


def print_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers)]
    lines.extend(fmt.format(*[str(c) for c in row]) for row in rows)
    return "\n".join(lines)
