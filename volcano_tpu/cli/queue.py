"""vcctl queue subcommands (reference: pkg/cli/queue/{create,list,get,delete,
operate}.go)."""

from __future__ import annotations

import time

from ..models.objects import Command, JobAction, ObjectMeta, Queue, QueueSpec
from .util import parse_resource_list, print_table

ACTION_OPEN = "open"
ACTION_CLOSE = "close"
ACTION_UPDATE = "update"


def create_queue(client, name: str, weight: int = 1,
                 capability: str = "") -> str:
    """pkg/cli/queue/create.go"""
    if not name:
        raise ValueError("queue name must be specified")
    queue = Queue(metadata=ObjectMeta(name=name),
                  spec=QueueSpec(
                      weight=weight,
                      capability=parse_resource_list(capability)
                      if capability else None))
    client.create("queues", queue)
    return f"create queue {name} successfully"


def _queue_rows(queues):
    rows = []
    for q in queues:
        rows.append([q.metadata.name, q.spec.weight, q.status.state or "Open",
                     q.status.inqueue, q.status.pending, q.status.running,
                     q.status.unknown])
    return rows


def list_queues(client) -> str:
    """pkg/cli/queue/list.go"""
    queues = sorted(client.list("queues"), key=lambda q: q.metadata.name)
    return print_table(
        ["Name", "Weight", "State", "Inqueue", "Pending", "Running", "Unknown"],
        _queue_rows(queues))


def get_queue(client, name: str) -> str:
    """pkg/cli/queue/get.go"""
    if not name:
        raise ValueError("queue name must be specified")
    q = client.get("queues", name)
    if q is None:
        raise ValueError(f"queue {name} not found")
    return print_table(
        ["Name", "Weight", "State", "Inqueue", "Pending", "Running", "Unknown"],
        _queue_rows([q]))


def delete_queue(client, name: str) -> str:
    """pkg/cli/queue/delete.go — admission enforces Closed-state-only."""
    if not name:
        raise ValueError("queue name must be specified")
    client.delete("queues", name)
    return f"delete queue {name} successfully"


def operate_queue(client, name: str, action: str, weight: int = 0) -> str:
    """pkg/cli/queue/operate.go:65-99 — open/close via Command, update=weight."""
    if not name:
        raise ValueError("queue name must be specified")
    if action == ACTION_OPEN:
        cmd_action = JobAction.OPEN_QUEUE
    elif action == ACTION_CLOSE:
        cmd_action = JobAction.CLOSE_QUEUE
    elif action == ACTION_UPDATE:
        if weight <= 0:
            raise ValueError(
                f"when {ACTION_UPDATE} a queue, weight must be specified, "
                f"the value must be greater than 0")
        q = client.get("queues", name)
        if q is None:
            raise ValueError(f"queue {name} not found")
        q.spec.weight = weight
        client.update("queues", q)
        return f"update queue {name} successfully"
    else:
        raise ValueError(
            f"invalid queue action {action!r}, valid actions are "
            f"{ACTION_OPEN}, {ACTION_CLOSE}, {ACTION_UPDATE}")
    if client.get("queues", name) is None:
        raise ValueError(f"queue {name} not found")
    client.create("commands", Command(
        metadata=ObjectMeta(
            name=f"{name}-{action}-{int(time.time() * 1000) % 100000}"),
        action=cmd_action, target_kind="Queue", target_name=name))
    return f"{action} queue {name} successfully"
