"""vcctl job subcommands (reference: pkg/cli/job/{run,list,view,suspend,
resume,delete}.go)."""

from __future__ import annotations

import time
from typing import Optional

from ..models import objects as obj
from ..models.objects import (Command, Container, Job, JobAction, JobSpec,
                              ObjectMeta, PodSpec, PodTemplate, TaskSpec)
from .util import parse_resource_list, print_table


def run_job(client, name: str, namespace: str = "default",
            image: str = "busybox", replicas: int = 1, min_available: int = 1,
            requests: str = "cpu=1000m,memory=100Mi",
            limits: str = "cpu=1000m,memory=100Mi",
            scheduler: str = obj.DEFAULT_SCHEDULER_NAME,
            queue: str = obj.DEFAULT_QUEUE,
            filename: Optional[str] = None) -> str:
    """pkg/cli/job/run.go:70-112"""
    if not name and not filename:
        raise ValueError("job name cannot be left blank")
    if filename:
        job = load_job_file(filename)
    else:
        job = Job(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=JobSpec(
                min_available=min_available, queue=queue,
                scheduler_name=scheduler,
                tasks=[TaskSpec(
                    name=name, replicas=replicas,
                    template=PodTemplate(
                        metadata=ObjectMeta(name=name),
                        spec=PodSpec(containers=[Container(
                            name=name, image=image,
                            requests=parse_resource_list(requests),
                            limits=parse_resource_list(limits))])))]))
    created = client.create("jobs", job)
    return f"run job {created.metadata.name} successfully"


def load_job_file(filename: str) -> Job:
    """-f job.yaml (run.go readFile); YAML shape mirrors the CRD."""
    import yaml

    from ..apiserver.codec import decode_object
    with open(filename) as f:
        data = yaml.safe_load(f)
    # accept both wire-format dicts and k8s-style manifests
    if "apiVersion" in data or "kind" in data:
        meta = data.get("metadata", {})
        spec = data.get("spec", {})
        tasks = []
        for t in spec.get("tasks", []):
            template = t.get("template", {})
            pod_spec = template.get("spec", {})
            containers = [Container(
                name=c.get("name", "main"), image=c.get("image", ""),
                requests=(c.get("resources", {}) or {}).get("requests", {}),
                limits=(c.get("resources", {}) or {}).get("limits", {}),
                command=c.get("command", []))
                for c in pod_spec.get("containers", [])]
            tasks.append(TaskSpec(
                name=t.get("name", ""), replicas=t.get("replicas", 1),
                min_available=t.get("minAvailable"),
                template=PodTemplate(spec=PodSpec(containers=containers))))
        return Job(
            metadata=ObjectMeta(name=meta.get("name", ""),
                                namespace=meta.get("namespace", "default")),
            spec=JobSpec(
                min_available=spec.get("minAvailable", 0),
                queue=spec.get("queue", obj.DEFAULT_QUEUE),
                scheduler_name=spec.get("schedulerName",
                                        obj.DEFAULT_SCHEDULER_NAME),
                max_retry=spec.get("maxRetry", 0),
                plugins=spec.get("plugins", {}),
                tasks=tasks))
    return decode_object("jobs", data)


def list_jobs(client, namespace: str = "default", all_namespaces: bool = False,
              scheduler: str = "", selector: str = "") -> str:
    """pkg/cli/job/list.go:95-160"""
    jobs = client.list("jobs", None if all_namespaces else namespace)
    headers = ["Name", "Creation", "Phase", "JobType", "Replicas", "Min",
               "Pending", "Running", "Succeeded", "Failed", "Unknown",
               "RetryCount"]
    if all_namespaces:
        headers.insert(0, "Namespace")
    rows = []
    for job in jobs:
        if scheduler and job.spec.scheduler_name != scheduler:
            continue
        if selector and selector not in job.metadata.name:
            continue
        replicas = sum(t.replicas for t in job.spec.tasks)
        created = time.strftime(
            "%Y-%m-%d", time.localtime(job.metadata.creation_timestamp)) \
            if job.metadata.creation_timestamp else "-"
        row = [job.metadata.name, created, job.status.state.phase or "-",
               "batch", replicas, job.spec.min_available,
               job.status.pending, job.status.running, job.status.succeeded,
               job.status.failed, job.status.unknown, job.status.retry_count]
        if all_namespaces:
            row.insert(0, job.metadata.namespace)
        rows.append(row)
    return print_table(headers, rows)


def view_job(client, name: str, namespace: str = "default") -> str:
    """pkg/cli/job/view.go — job + its pods"""
    if not name:
        raise ValueError("job name must be specified")
    job = client.get("jobs", name, namespace)
    if job is None:
        raise ValueError(f"job {namespace}/{name} not found")
    lines = [
        f"Name:       {job.metadata.name}",
        f"Namespace:  {job.metadata.namespace}",
        f"Queue:      {job.spec.queue}",
        f"Scheduler:  {job.spec.scheduler_name}",
        f"Phase:      {job.status.state.phase or '-'}",
        f"MinAvailable: {job.spec.min_available}",
        f"RetryCount: {job.status.retry_count}",
        "Tasks:",
    ]
    for t in job.spec.tasks:
        lines.append(f"  - {t.name}: replicas={t.replicas}"
                     + (f" minAvailable={t.min_available}"
                        if t.min_available is not None else ""))
    pods = [p for p in client.list("pods", namespace)
            if p.metadata.annotations.get(obj.JOB_NAME_KEY) == name]
    if pods:
        lines.append("Pods:")
        for p in sorted(pods, key=lambda p: p.metadata.name):
            lines.append(f"  - {p.metadata.name}: phase={p.status.phase} "
                         f"node={p.spec.node_name or '-'}")
    return "\n".join(lines)


def _create_job_command(client, namespace: str, name: str, action: str) -> None:
    """pkg/cli/util createJobCommand — Command CR targeted at the job."""
    job = client.get("jobs", name, namespace)
    if job is None:
        raise ValueError(f"job {namespace}/{name} not found")
    cmd = Command(
        metadata=ObjectMeta(
            name=f"{name}-{action.lower()}-{int(time.time() * 1000) % 100000}",
            namespace=namespace),
        action=action, target_kind="Job", target_name=name)
    client.create("commands", cmd)


def suspend_job(client, name: str, namespace: str = "default") -> str:
    """pkg/cli/job/suspend.go — AbortJob command"""
    if not name:
        raise ValueError("job name is mandatory to suspend a particular job")
    _create_job_command(client, namespace, name, JobAction.ABORT_JOB)
    return f"suspend job {name} successfully"


def resume_job(client, name: str, namespace: str = "default") -> str:
    """pkg/cli/job/resume.go — ResumeJob command"""
    if not name:
        raise ValueError("job name is mandatory to resume a particular job")
    _create_job_command(client, namespace, name, JobAction.RESUME_JOB)
    return f"resume job {name} successfully"


def delete_job(client, name: str, namespace: str = "default") -> str:
    """pkg/cli/job/delete.go"""
    if not name:
        raise ValueError("job name is mandatory to delete a particular job")
    client.delete("jobs", name, namespace)
    return f"delete job {name} successfully"
