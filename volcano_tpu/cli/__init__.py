"""CLI (reference: pkg/cli + cmd/cli): vcctl plus the single-verb tools."""

from .vcctl import build_parser, dispatch, main

__all__ = ["build_parser", "dispatch", "main"]
