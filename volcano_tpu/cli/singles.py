"""Standalone single-verb CLIs (reference: cmd/cli/{vsub,vcancel,vjobs,
vqueues,vresume,vsuspend}/main.go) — each forwards to the matching vcctl
verb so `python -m volcano_tpu.cli.singles vsub --name j1 ...` (or the
console scripts) behaves like `vcctl job run`."""

from __future__ import annotations

import sys
from typing import List, Optional

from .vcctl import main as vcctl_main

VERB_MAP = {
    "vsub": ["job", "run"],
    "vcancel": ["job", "delete"],
    "vjobs": ["job", "list"],
    "vqueues": ["queue", "list"],
    "vresume": ["job", "resume"],
    "vsuspend": ["job", "suspend"],
}


def run_single(tool: str, argv: Optional[List[str]] = None, client=None) -> int:
    if tool not in VERB_MAP:
        print(f"unknown tool {tool}", file=sys.stderr)
        return 1
    return vcctl_main(VERB_MAP[tool] + list(argv or []), client=client)


def _make_main(tool: str):
    def main(argv: Optional[List[str]] = None) -> int:
        return run_single(tool, argv if argv is not None else sys.argv[1:])
    return main


vsub = _make_main("vsub")
vcancel = _make_main("vcancel")
vjobs = _make_main("vjobs")
vqueues = _make_main("vqueues")
vresume = _make_main("vresume")
vsuspend = _make_main("vsuspend")


if __name__ == "__main__":
    tool, rest = sys.argv[1], sys.argv[2:]
    sys.exit(run_single(tool, rest))
