"""Scheduler: the periodic cycle driver (reference: pkg/scheduler/
scheduler.go): load conf (hot-reloadable), every period open a session, run
the configured actions in order, close the session.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)

from .apiserver.store import ObjectStore
from .cache import SchedulerCache
from .framework import (close_session, default_scheduler_conf, get_action,
                        open_session, parse_scheduler_conf)
from .metrics import metrics as m
from .models.objects import DEFAULT_SCHEDULER_NAME
from .utils.clock import Clock
from .utils.filewatcher import FileWatcher


class Scheduler:
    # cycle watchdog (docs/design/resilience.md): a run_once exceeding
    # watchdog_multiple x schedule_period wall seconds logs the in-flight
    # flight-recorder phase breakdown, bumps
    # volcano_cycle_deadline_exceeded_total, and marks the scheduler
    # degraded on /debug/health (cleared by the next in-deadline cycle).
    # The watchdog only observes — it never interrupts the cycle — so
    # scheduling decisions stay bit-reproducible.
    WATCHDOG_MULTIPLE = 4.0

    # anti-entropy cadence in the threaded run() loop: one cache<->store
    # fingerprint pass (docs/design/failover.md) every N cycles, in the
    # inter-cycle gap. 0 disables. The simulator paces its own passes at
    # the tick barrier instead.
    ANTI_ENTROPY_EVERY_CYCLES = 60

    def __init__(self, store: ObjectStore,
                 scheduler_name: str = DEFAULT_SCHEDULER_NAME,
                 scheduler_conf: Optional[str] = None,
                 scheduler_conf_path: Optional[str] = None,
                 schedule_period: float = 1.0,
                 cache: Optional[SchedulerCache] = None,
                 clock: Optional[Clock] = None,
                 watchdog_multiple: Optional[float] = None,
                 elector=None,
                 anti_entropy_every: Optional[int] = None,
                 incremental: Optional[bool] = None):
        self.store = store
        # time-dependent scheduling decisions (sla waiting windows, ...)
        # read this clock via the session (run_once passes it into
        # open_session), so a simulator driving the scheduler on a
        # virtual clock stays coherent with the store's creation
        # timestamps
        self.clock = clock if clock is not None else store.clock
        self.cache = cache if cache is not None else SchedulerCache(
            store, scheduler_name)
        self.schedule_period = schedule_period
        self.watchdog_multiple = (watchdog_multiple
                                  if watchdog_multiple is not None
                                  else self.WATCHDOG_MULTIPLE)
        # leader election + fencing (docs/design/failover.md): with an
        # elector attached, run_once is a no-op while standby (the
        # /debug/pending report says so explicitly), and the cache stamps
        # its bind/patch writes with the elector's fencing token so a
        # deposed incarnation can't write after a takeover.
        self.elector = elector
        if elector is not None and \
                getattr(self.cache, "fence_source", None) is None:
            self.cache.fence_source = lambda: elector.fencing_token
        self.anti_entropy_every = (anti_entropy_every
                                   if anti_entropy_every is not None
                                   else self.ANTI_ENTROPY_EVERY_CYCLES)
        # incremental steady-state cycle (docs/design/
        # incremental_cycle.md): the production default. The cache keeps
        # a persistent snapshot patched per dirty job/node instead of
        # re-cloning the cluster every period; periodic full recomputes
        # and the anti-entropy pass bound any tracking bug. Pass
        # incremental=False to force the legacy full rebuild per cycle.
        self.incremental = incremental if incremental is not None else True
        if hasattr(self.cache, "incremental"):
            self.cache.incremental = self.incremental
        self.degraded = False
        self.cycle_deadline_exceeded = 0
        self._conf_path = scheduler_conf_path
        self._mutex = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[FileWatcher] = None
        if scheduler_conf is not None:
            self.conf = parse_scheduler_conf(scheduler_conf)
        elif scheduler_conf_path is not None:
            with open(scheduler_conf_path) as f:
                self.conf = parse_scheduler_conf(f.read())
        else:
            self.conf = default_scheduler_conf()

    # -- conf hot reload (scheduler.go:60-68,122-170) ----------------------

    def load_scheduler_conf(self) -> None:
        """Re-read the conf file; keep the previous conf on parse errors
        (validation-or-keep-previous, scheduler.go:122-135)."""
        if self._conf_path is None:
            return
        try:
            with open(self._conf_path) as f:
                new_conf = parse_scheduler_conf(f.read())
            if not new_conf.actions:
                # an empty document (e.g. the file read mid-rewrite) parses
                # cleanly but is never a valid scheduler conf
                raise ValueError("conf has no actions")
            for name in new_conf.actions:
                if get_action(name) is None:
                    raise ValueError(f"unknown action {name!r}")
            with self._mutex:
                self.conf = new_conf
        except Exception as e:
            # validation-or-keep-previous: the running conf stays in effect
            log.warning("scheduler conf reload failed, keeping previous: %s", e)

    def watch_conf(self) -> None:
        if self._conf_path is None:
            return
        self._watcher = FileWatcher(self._conf_path,
                                    on_change=lambda: self.load_scheduler_conf())
        self._watcher.start()

    # -- cycle -------------------------------------------------------------

    def run_once(self) -> None:
        """One scheduling cycle (scheduler.go:90-110).

        The cyclic garbage collector is paused for the duration of the
        cycle: a 50k-task snapshot churns millions of (acyclic — refcount
        reclaimed) objects and a mid-cycle gen2 scan costs over a second.
        Cycle-created garbage with actual reference cycles is collected
        between cycles in :meth:`run`."""
        from .trace import tracer as tr
        from .utils import gcguard
        if self.elector is not None and not self.elector.is_leader:
            # standby: scheduling is the leader's job. Surface the reason
            # on /debug/pending instead of silently doing nothing — the
            # exact failover window operators page on.
            from .trace import pending
            pending.publish_idle(
                pending.REASON_NOT_LEADER,
                detail=f"candidate {self.elector.identity!r} is waiting "
                       f"on the lease")
            return
        start = time.perf_counter()
        with self._mutex:
            conf = self.conf
        deadline = self.schedule_period * self.watchdog_multiple
        timer: Optional[threading.Timer] = None
        if deadline > 0:
            timer = threading.Timer(deadline, self._watchdog_fire,
                                    args=(deadline,))
            timer.daemon = True
            timer.start()
        try:
            with tr.cycle():
                gcguard.pause()
                begin = getattr(self.cache, "begin_cycle", None)
                if begin is not None:
                    begin()
                try:
                    ssn = open_session(self.cache, conf.tiers,
                                       conf.configurations, clock=self.clock,
                                       actions=conf.actions)
                    tr.tag_cycle(jobs=len(ssn.jobs), nodes=len(ssn.nodes),
                                 queues=len(ssn.queues))
                    stats = getattr(self.cache, "last_snapshot_stats", None)
                    if stats:
                        # /debug/cycles: snapshot mode + the dirty-set
                        # sizes this cycle consumed
                        tr.tag_cycle(mode=stats.get("mode"),
                                     dirty_jobs=stats.get("dirty_jobs"),
                                     dirty_nodes=stats.get("dirty_nodes"),
                                     quiet=stats.get("quiet"))
                    try:
                        for name in conf.actions:
                            action = get_action(name)
                            if action is None:
                                continue
                            with m.action_timer(name), \
                                    tr.span(f"action:{name}", action=name):
                                action.execute(ssn)
                    finally:
                        close_session(ssn)
                finally:
                    end = getattr(self.cache, "end_cycle", None)
                    if end is not None:
                        end()
                    gcguard.resume()
        finally:
            elapsed = time.perf_counter() - start
            if timer is not None:
                timer.cancel()
                if self.degraded and elapsed <= deadline:
                    # recovered: this cycle came in under the deadline
                    self.degraded = False
                    m.set_health("scheduler", True,
                                 "cycle time back under the watchdog "
                                 "deadline")
        m.update_e2e_duration(elapsed)
        if tr.is_enabled():
            # /debug/timeseries: one sample of the key gauges/counters
            # per cycle (docs/design/observability.md) — rides the same
            # production switch as the flight recorder
            from .metrics import timeseries
            timeseries.sample(self.clock.now(), extra={
                "cycle_ms": round(elapsed * 1000.0, 3),
                "seq": tr.current_seq()})

    def _watchdog_fire(self, deadline: float) -> None:
        """The cycle blew its watchdog deadline: record the breach and
        the stuck cycle's flight-recorder phase breakdown. Observation
        only — the cycle keeps running and will complete (or fail) on
        its own; the next in-deadline cycle clears the degraded mark."""
        from .trace import tracer as tr
        self.degraded = True
        self.cycle_deadline_exceeded += 1
        m.inc(m.CYCLE_DEADLINE_EXCEEDED)
        detail = (f"scheduling cycle exceeded its {deadline:.2f}s watchdog "
                  f"deadline ({self.watchdog_multiple:g}x the "
                  f"{self.schedule_period:g}s period)")
        m.set_health("scheduler", False, detail)
        phases = tr.live_phases()
        log.error("cycle watchdog: %s; in-flight phases: %s", detail,
                  phases if phases else "(tracing disabled)")

    def run(self) -> None:
        """Start cache ingestion + periodic cycles until stop()."""
        import gc
        self.cache.run()
        self.watch_conf()
        # long-lived startup objects never need cycle detection; freezing
        # them keeps inter-cycle collections proportional to per-cycle
        # garbage, not to cluster size
        gc.collect()
        gc.freeze()
        cycles = 0
        while not self._stop.is_set():
            cycle_start = time.monotonic()   # lint: allow(clock-discipline): daemon-loop pacing only; determinism gates drive run_once() directly on the injected clock
            try:
                self.run_once()
            except Exception:
                # a transient failure (e.g. a status-writeback conflict) must
                # not kill the scheduling thread; next cycle resyncs
                log.exception("scheduling cycle failed; retrying next period")
            cycles += 1
            if self.anti_entropy_every and \
                    cycles % self.anti_entropy_every == 0:
                try:
                    # inter-cycle gap: executors may still be draining a
                    # flush; the pass tolerates staged-but-uncommitted
                    # binds (rv-based fingerprints, see cache.anti_entropy)
                    self.cache.anti_entropy()
                except Exception:
                    log.exception("anti-entropy pass failed; next "
                                  "interval retries")
            gc.collect(0)   # reap cycle-garbage with true ref cycles
            elapsed = time.monotonic() - cycle_start   # lint: allow(clock-discipline): daemon-loop pacing only (monotonic is immune to wall jumps; never feeds a scheduling decision)
            self._stop.wait(max(0.0, self.schedule_period - elapsed))

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.stop()
