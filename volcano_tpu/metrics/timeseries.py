"""Metrics time-series ring: the last N cycles of key gauges/counters.

``/metrics`` answers "what is the value now"; a hung cycle, a bind-error
burst or a fenced-write spike is only diagnosable from the SHAPE of the
last few minutes. ``sample()`` — called once per scheduling cycle from
``Scheduler.run_once`` while tracing is enabled — snapshots a fixed
whitelist of counters/gauges plus caller-supplied extras (cycle wall
time, cycle seq) into a bounded ring served at ``/debug/timeseries``,
written into sim repro bundles (``timeseries.json``) and attached to
``bench.py``'s JSON row.

Sizing: ``CAPACITY`` = 512 samples. At the production 1 s schedule
period that is ~8.5 minutes of history; one sample is a flat dict of a
dozen floats (~300 B), so the ring tops out around 150 KB — cheap
enough to leave on. Timestamps come from the caller's clock (virtual
under the sim), but wall-time extras (cycle_ms) make the ring itself
excluded from the sim's bit-identical fingerprints by design.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

from . import metrics as m

CAPACITY = 512

_lock = threading.Lock()
_ring: deque = deque(maxlen=CAPACITY)

# counters sampled by name (summed over label sets) — the signals every
# open ROADMAP item is gated on
COUNTER_KEYS = (
    m.SCHEDULE_ATTEMPTS,
    m.BIND_FLUSH_BINDS,
    m.BIND_ERRORS,
    m.RESYNC_RETRIES,
    m.GANG_HEALS,
    m.FENCED_WRITES,
    m.CACHE_DIVERGENCE,
    m.WATCH_RESTARTS,
    m.UNSCHEDULABLE_REASON,
    m.SOLVER_FALLBACK,
    m.SOLVER_SHAPE_RECOMPILES,
    m.DEVICE_TRANSFER_BYTES,
)
GAUGE_KEYS = (m.QUARANTINED_TASKS,)
# histograms sampled as (count, sum) pairs
HIST_KEYS = (m.E2E_SCHEDULING_LATENCY, m.POD_E2E_LATENCY,
             m.BIND_FLUSH_LATENCY, m.SOLVER_KERNEL_LATENCY)


def configure(capacity: int) -> None:
    global _ring
    capacity = max(1, int(capacity))
    with _lock:
        if _ring.maxlen != capacity:
            _ring = deque(_ring, maxlen=capacity)


def reset() -> None:
    with _lock:
        _ring.clear()


def sample(now: float, extra: Optional[Dict] = None) -> dict:
    """Capture one per-cycle sample into the ring and return it. Uses
    ``metrics.collect`` — one locked registry pass, no copies — because
    this runs on the cycle hot path whenever tracing is on."""
    counters, gauges, hists = m.collect(COUNTER_KEYS, GAUGE_KEYS,
                                        HIST_KEYS)
    row: Dict[str, float] = {"t": round(now, 6)}
    for name, total in counters.items():
        if total:
            row[name] = round(total, 3)
    for name, total in gauges.items():
        if total:
            row[name] = round(total, 3)
    for name, (count, total) in hists.items():
        if count:
            row[f"{name}_count"] = count
            row[f"{name}_sum"] = round(total, 3)
    if extra:
        row.update(extra)
    with _lock:
        _ring.append(row)
    return row


def series(limit: Optional[int] = None) -> list:
    """Ring contents, oldest first (``limit`` keeps only the newest N)."""
    with _lock:
        rows = list(_ring)
    return rows[-limit:] if limit else rows
