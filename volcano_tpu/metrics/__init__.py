from . import metrics  # noqa: F401
