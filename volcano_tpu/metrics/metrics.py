"""Prometheus-style metrics (reference: pkg/scheduler/metrics/*.go).

The metric names mirror the reference's (namespace ``volcano``) so dashboards
translate directly. Without a hard prometheus_client dependency, metrics are
kept in-process (counters/gauges/histogram summaries) and can be scraped via
``render_prometheus()`` which emits the text exposition format.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Tuple

_lock = threading.Lock()


class _Hist:
    __slots__ = ("count", "total", "buckets")
    # log-spaced to cover metrics recorded in seconds, milliseconds and
    # microseconds alike (the reference's units vary per metric)
    BOUNDS = (0.0001, 0.001, 0.01, 0.1, 1.0, 10.0, 100.0,
              1e3, 1e4, 1e5, 1e6, 1e7)

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.buckets = [0] * (len(self.BOUNDS) + 1)

    def observe(self, v: float):
        self.count += 1
        self.total += v
        for i, b in enumerate(self.BOUNDS):
            if v <= b:
                self.buckets[i] += 1
                return
        self.buckets[-1] += 1


_histograms: Dict[Tuple[str, Tuple], _Hist] = defaultdict(_Hist)
_gauges: Dict[Tuple[str, Tuple], float] = {}
_counters: Dict[Tuple[str, Tuple], float] = defaultdict(float)

NS = "volcano"

E2E_SCHEDULING_LATENCY = f"{NS}_e2e_scheduling_latency_milliseconds"
E2E_JOB_SCHEDULING_LATENCY = f"{NS}_e2e_job_scheduling_latency_milliseconds"
PLUGIN_LATENCY = f"{NS}_plugin_scheduling_latency_microseconds"
ACTION_LATENCY = f"{NS}_action_scheduling_latency_microseconds"
TASK_LATENCY = f"{NS}_task_scheduling_latency_milliseconds"
SCHEDULE_ATTEMPTS = f"{NS}_schedule_attempts_total"
PREEMPTION_VICTIMS = f"{NS}_pod_preemption_victims"
PREEMPTION_ATTEMPTS = f"{NS}_total_preemption_attempts"
UNSCHEDULE_TASK_COUNT = f"{NS}_unschedule_task_count"
UNSCHEDULE_JOB_COUNT = f"{NS}_unschedule_job_count"
QUEUE_ALLOCATED = f"{NS}_queue_allocated_milli_cpu"
QUEUE_DESERVED = f"{NS}_queue_deserved_milli_cpu"
QUEUE_SHARE = f"{NS}_queue_share"
QUEUE_WEIGHT = f"{NS}_queue_weight"
NAMESPACE_SHARE = f"{NS}_namespace_share"
NAMESPACE_WEIGHT = f"{NS}_namespace_weight"
SOLVER_KERNEL_LATENCY = f"{NS}_tpu_solver_kernel_latency_milliseconds"
UNSCHEDULABLE_REASON = f"{NS}_unschedulable_reason_total"
# bind-flush pipeline (docs/design/bind_pipeline.md): wall latency of one
# coalesced drain (apply + store write + echo ingest), binds it carried,
# and the shard fan-out of each sharded store commit
BIND_FLUSH_LATENCY = f"{NS}_bind_flush_latency_milliseconds"
BIND_FLUSH_BINDS = f"{NS}_bind_flush_binds_total"
STORE_PATCH_SHARDS = f"{NS}_store_patch_shards"
# the flush_wall residue (docs/design/bind_pipeline.md): the two
# non-bind executor tasks the post-cycle drain also waits on — the
# session's PodGroup status writeback and the inter-cycle snapshot
# prebuild — split into their own budget lines so the commit-path tail
# stays attributable at the 10x shape
STATUS_WRITEBACK_LATENCY = f"{NS}_status_writeback_latency_milliseconds"
SNAPSHOT_PREBUILD_LATENCY = f"{NS}_snapshot_prebuild_latency_milliseconds"
# commit-path resilience (docs/design/resilience.md): bind failures by
# reason, resync retry volume, pods quarantined after budget exhaustion,
# gang-atomic heal events, the cycle watchdog, and the solver kernel
# circuit breaker's fallback transitions / open state
BIND_ERRORS = f"{NS}_bind_errors_total"
RESYNC_RETRIES = f"{NS}_resync_retries_total"
QUARANTINED_TASKS = f"{NS}_quarantined_tasks"
GANG_HEALS = f"{NS}_gang_heal_total"
CYCLE_DEADLINE_EXCEEDED = f"{NS}_cycle_deadline_exceeded_total"
SOLVER_FALLBACK = f"{NS}_solver_fallback_total"
SOLVER_BREAKER_OPEN = f"{NS}_solver_breaker_open"
# which kernel tier actually served each placement (sharded / pallas /
# native / chunked / scan) — the auto-selection proof for the mesh
# default (docs/design/sharded_kernel.md)
SOLVER_KERNEL_RUNS = f"{NS}_solver_kernel_runs_total"
# control-plane failover (docs/design/failover.md): writes rejected for a
# superseded fencing token, cache-vs-store anti-entropy divergences by
# kind, remote-store transient write retries, and watch-stream restarts
FENCED_WRITES = f"{NS}_fenced_writes_total"
CACHE_DIVERGENCE = f"{NS}_cache_divergence_total"
STORE_WRITE_RETRIES = f"{NS}_store_write_retries_total"
WATCH_RESTARTS = f"{NS}_watch_restarts_total"
# pod lifecycle telemetry (docs/design/observability.md): end-to-end
# submission->echo-confirmed latency per queue and per-hop latency of the
# ledger's transition chain (trace/ledger.py), observed at completion
POD_E2E_LATENCY = f"{NS}_pod_e2e_latency_milliseconds"
POD_HOP_LATENCY = f"{NS}_pod_hop_latency_milliseconds"
# solver & backend profiling hooks: placement-kernel dispatches by
# compile-cache outcome (result="hit"|"miss"), recompiles forced by a NEW
# padded-shape bucket of an already-seen kernel (the shape-churn signal),
# host->device bytes staged as kernel inputs, and backend-init probe
# verdicts (outcome="alive"|"dead"|"hang")
SOLVER_COMPILE_CACHE = f"{NS}_solver_compile_cache_total"
SOLVER_SHAPE_RECOMPILES = f"{NS}_solver_padded_shape_recompile_total"
DEVICE_TRANSFER_BYTES = f"{NS}_solver_device_transfer_bytes_total"
BACKEND_PROBE = f"{NS}_backend_probe_total"
# incremental steady-state cycle (docs/design/incremental_cycle.md):
# snapshots by mode (mode="full"|"incremental"), the dirty-set sizes the
# last snapshot consumed (kind="jobs"|"nodes"), and the solver's
# persistent device-resident node buffers (event="reuse"|"rebuild")
CYCLE_MODE = f"{NS}_cycle_mode_total"
DIRTY_SET_SIZE = f"{NS}_dirty_set_size"
SOLVER_DEVICE_BUFFER = f"{NS}_solver_device_buffer_total"
# constraint compilation (docs/design/constraints.md): per-pass build
# latency, node rows refreshed by the persistent-state sync
# (event="refresh"), compile crashes that fell back to the per-task
# Python reference, and victim-selection kernel engagements
# (mode="kernel"|"python")
CONSTRAINT_BUILD_LATENCY = f"{NS}_constraint_build_latency_milliseconds"
CONSTRAINT_BUILD_RUNS = f"{NS}_constraint_build_runs_total"
CONSTRAINT_ROWS = f"{NS}_constraint_rows_total"
CONSTRAINT_FALLBACK = f"{NS}_constraint_fallback_total"
VICTIM_SELECT_RUNS = f"{NS}_victim_select_runs_total"
VICTIM_SELECT_LATENCY = f"{NS}_victim_select_latency_milliseconds"
# multi-tenant serving hub (docs/design/serving.md): per-frame fan-out
# latency, coalesced frame/event volumes (their ratio is the coalescing
# proof), structured cursor relists pushed by the hub, per-tenant
# admission verdicts at the write/watch edge, per-shard outbox depth,
# and the RemoteStore's explicit cursor-gap relists (the client half of
# the structured "gone" contract)
SERVING_FANOUT_LATENCY = f"{NS}_serving_fanout_latency_milliseconds"
SERVING_BATCHES = f"{NS}_serving_batches_total"
SERVING_EVENTS = f"{NS}_serving_events_total"
SERVING_RELISTS = f"{NS}_serving_relists_total"
SERVING_ADMITTED = f"{NS}_serving_admitted_total"
SERVING_THROTTLED = f"{NS}_serving_throttled_total"
SERVING_SHARD_DEPTH = f"{NS}_serving_hub_shard_depth"
SERVING_SHARD_BACKPRESSURE = f"{NS}_serving_hub_shard_backpressure"
WATCH_RELISTS = f"{NS}_watch_relists_total"
# placement explainer + pruning-readiness surface (docs/design/
# observability.md): per-gang feasible-node-count and top-k
# score-mass-coverage histograms (labeled k=<shortlist width>) — the
# baseline the candidate-pruning ROADMAP item shortlists against —
# plus the fleet fragmentation gauge (largest schedulable uniform-gang
# vs total free capacity, the Tesserae defrag pre-metric), per-shard
# occupancy/pressure gauges off the ShardPlan, and padded-vs-live
# waste ratios per kernel axis
GANG_FEASIBLE_NODES = f"{NS}_gang_feasible_nodes"
TOPK_SCORE_COVERAGE = f"{NS}_topk_score_coverage"
FRAGMENTATION_RATIO = f"{NS}_fragmentation_ratio"
SHARD_OCCUPANCY = f"{NS}_shard_occupancy"
SHARD_PRESSURE = f"{NS}_shard_pressure"
SHARD_PRESSURE_IMBALANCE = f"{NS}_shard_pressure_imbalance"
PADDED_WASTE = f"{NS}_padded_waste_ratio"
# candidate pruning + two-level placement (docs/design/pruning.md):
# place() calls served by the reduced shortlist kernel
# (level="single"|"two_level"), fallbacks to the full-width kernel by
# reason (reason="low_coverage"|"shortlist_exhausted"|"wide_union"|
# "empty_union"|"crash" — the loss-guard contract: pruning never loses
# a placement the dense kernel would have made), and the width of the
# last reduced node axis (the union of every gang's shortlist)
PRUNE_RUNS = f"{NS}_prune_runs_total"
PRUNE_FALLBACK = f"{NS}_prune_fallback_total"
PRUNE_UNION_WIDTH = f"{NS}_prune_union_width"
# federated control plane (docs/design/federation.md): journal frames /
# events replicated leader->follower, contiguity gaps detected at the
# follower (each one triggers a structured catch-up), snapshot
# bootstraps, frames REJECTED because they carried a stale leader epoch
# (the fencing-token contract — a deposed leader cannot ship history),
# per-follower replication lag in rvs, cursor handoffs served by a peer
# replica's hub after failover, and cross-replica anti-entropy
# fingerprint audits by verdict (verdict="identical"|"divergent")
REPLICATION_FRAMES = f"{NS}_replication_frames_total"
REPLICATION_EVENTS = f"{NS}_replication_events_total"
REPLICATION_GAPS = f"{NS}_replication_gaps_total"
REPLICATION_SNAPSHOTS = f"{NS}_replication_snapshots_total"
REPLICATION_FENCED = f"{NS}_replication_fenced_frames_total"
REPLICATION_LAG = f"{NS}_replication_follower_lag_rvs"
REPLICATION_HANDOFFS = f"{NS}_replication_cursor_handoffs_total"
REPLICATION_AUDITS = f"{NS}_replication_fingerprint_audits_total"

# write-ahead-log durability (PR 20, docs/design/durability.md):
# append batches accepted from the store's journal hook, framed records
# and journal entries written, group-commit fsyncs + their latency, the
# durable rv watermark (everything at or below survived a crash), the
# read-only degradation gauge (1 while ENOSPC/EIO has the write path
# returning structured 503s), live segment count, snapshot-anchored
# compactions, recoveries replayed at startup, and torn final records
# truncated by recovery (expected after a mid-flush crash; anything
# further in is corruption and refuses to load)
WAL_APPENDS = f"{NS}_wal_appends_total"
WAL_RECORDS = f"{NS}_wal_records_total"
WAL_ENTRIES = f"{NS}_wal_entries_total"
WAL_FSYNCS = f"{NS}_wal_fsyncs_total"
WAL_FSYNC_MS = f"{NS}_wal_fsync_latency_milliseconds"
WAL_DURABLE_RV = f"{NS}_wal_durable_rv"
WAL_READ_ONLY = f"{NS}_wal_read_only"
WAL_SEGMENTS = f"{NS}_wal_segments"
WAL_COMPACTIONS = f"{NS}_wal_compactions_total"
WAL_RECOVERIES = f"{NS}_wal_recoveries_total"
WAL_TORN_TRUNCATIONS = f"{NS}_wal_torn_truncations_total"

# component health registry behind /debug/health: a component absent from
# the registry is healthy by default; the watchdog (scheduler.py) flips
# "scheduler" on a cycle-deadline breach and back on recovery
_health: Dict[str, Tuple[bool, str]] = {}


def set_health(component: str, healthy: bool, detail: str = ""):
    with _lock:
        _health[component] = (bool(healthy), detail)


def health_report() -> dict:
    """{"healthy": bool, "degraded": [component], "components": {...}} —
    the /debug/health payload (non-healthy renders as HTTP 503)."""
    with _lock:
        comps = {name: {"healthy": ok, "detail": detail}
                 for name, (ok, detail) in _health.items()}
    return {
        "healthy": all(c["healthy"] for c in comps.values()),
        "degraded": sorted(n for n, c in comps.items() if not c["healthy"]),
        "components": comps,
    }


def observe(name: str, value: float, **labels):
    with _lock:
        _histograms[(name, tuple(sorted(labels.items())))].observe(value)


def observe_bulk(name: str, values, **labels):
    """Observe a whole batch under ONE lock pass — the pod lifecycle
    ledger exports per-hop latencies for 50k-bind flush deliveries, and
    per-value locking would put ~300k lock acquisitions on the flush
    executor. Buckets resolve by bisect instead of the per-value bound
    scan (same first-bound->=value semantics), and the running total
    accumulates in the same per-value order as repeated observe()."""
    from bisect import bisect_left
    key = (name, tuple(sorted(labels.items())))
    with _lock:
        h = _histograms[key]
        bounds = h.BOUNDS
        buckets = h.buckets
        nb = len(bounds)
        h.count += len(values)
        total = h.total
        for v in values:
            total += v
            i = bisect_left(bounds, v)
            buckets[i if i < nb else -1] += 1
        h.total = total


def set_gauge(name: str, value: float, **labels):
    # single-label fast path: one-item tuples need no sort (the gauge
    # sweeps at session close set ~3 per job)
    items = tuple(labels.items())
    if len(items) > 1:
        items = tuple(sorted(items))
    with _lock:
        _gauges[(name, items)] = value


def inc(name: str, value: float = 1.0, **labels):
    with _lock:
        _counters[(name, tuple(sorted(labels.items())))] += value


def counter_total(name: str, **labels) -> float:
    """Current value of a counter series (exact labels), or the sum over
    every series of ``name`` when no labels are given — the read half
    the smoke gates use to assert a path actually ran."""
    with _lock:
        if labels:
            return _counters.get((name, tuple(sorted(labels.items()))), 0.0)
        return sum(v for (n, _), v in _counters.items() if n == name)


def histogram_total(name: str) -> float:
    """Summed observation total over every series of a histogram — the
    bench workers' delta reads (kernel/flush/constraint-build latency)."""
    with _lock:
        return sum(h.total for (n, _), h in _histograms.items()
                   if n == name)


@contextmanager
def plugin_timer(plugin: str, phase: str):
    start = time.perf_counter()
    try:
        yield
    finally:
        observe(PLUGIN_LATENCY, (time.perf_counter() - start) * 1e6,
                plugin=plugin, OnSession=phase)


@contextmanager
def action_timer(action: str):
    start = time.perf_counter()
    try:
        yield
    finally:
        observe(ACTION_LATENCY, (time.perf_counter() - start) * 1e6,
                action=action)


def update_e2e_duration(seconds: float):
    observe(E2E_SCHEDULING_LATENCY, seconds * 1000.0)


def update_unschedulable_task_count(job: str, count: int):
    set_gauge(UNSCHEDULE_TASK_COUNT, count, job=job)


def register_schedule_attempt(result: str):
    inc(SCHEDULE_ATTEMPTS, result=result)


def update_queue_allocated(queue: str, milli_cpu: float, memory: float):
    set_gauge(QUEUE_ALLOCATED, milli_cpu, queue_name=queue)
    set_gauge(f"{NS}_queue_allocated_memory_bytes", memory, queue_name=queue)


def update_queue_request(queue: str, milli_cpu: float, memory: float):
    set_gauge(f"{NS}_queue_request_milli_cpu", milli_cpu, queue_name=queue)
    set_gauge(f"{NS}_queue_request_memory_bytes", memory, queue_name=queue)


def update_queue_deserved(queue: str, milli_cpu: float, memory: float):
    set_gauge(QUEUE_DESERVED, milli_cpu, queue_name=queue)
    set_gauge(f"{NS}_queue_deserved_memory_bytes", memory, queue_name=queue)


def update_queue_share(queue: str, share: float):
    set_gauge(QUEUE_SHARE, share, queue_name=queue)


def update_queue_weight(queue: str, weight: int):
    set_gauge(QUEUE_WEIGHT, weight, queue_name=queue)


def update_queue_overused(queue: str, overused: bool):
    set_gauge(f"{NS}_queue_overused", 1.0 if overused else 0.0,
              queue_name=queue)


def update_namespace_share(namespace: str, share: float):
    set_gauge(NAMESPACE_SHARE, share, namespace=namespace)


def update_namespace_weight(namespace: str, weight: int):
    set_gauge(NAMESPACE_WEIGHT, weight, namespace=namespace)


def update_namespace_weighted_share(namespace: str, share: float):
    set_gauge(f"{NS}_namespace_weighted_share", share, namespace=namespace)


def update_job_share(namespace: str, job: str, share: float):
    set_gauge(f"{NS}_job_share", share, job_ns=namespace, job_id=job)


def update_preemption_victims(count: int):
    set_gauge(PREEMPTION_VICTIMS, count)


def register_preemption_attempt():
    inc(PREEMPTION_ATTEMPTS)


def reset():
    with _lock:
        _histograms.clear()
        _gauges.clear()
        _counters.clear()
        _health.clear()


def snapshot() -> dict:
    """Structured dump for tests and the /metrics endpoint."""
    with _lock:
        return {
            "histograms": {k: (h.count, h.total) for k, h in _histograms.items()},
            "gauges": dict(_gauges),
            "counters": dict(_counters),
        }


def collect(counter_names, gauge_names, hist_names) -> tuple:
    """Whitelist extraction in ONE locked pass with no registry copies:
    ``({counter: sum}, {gauge: sum}, {hist: (count, sum)})`` summed over
    label sets. The per-cycle timeseries sampler calls this on the hot
    path — ``snapshot()``'s three full dict copies per cycle measurably
    dented the <2% tracer-overhead budget at micro scale."""
    cset, gset, hset = set(counter_names), set(gauge_names), set(hist_names)
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, tuple] = {}
    with _lock:
        for (n, _), v in _counters.items():
            if n in cset:
                counters[n] = counters.get(n, 0.0) + v
        for (n, _), v in _gauges.items():
            if n in gset:
                gauges[n] = gauges.get(n, 0.0) + v
        for (n, _), h in _histograms.items():
            if n in hset:
                c, s = hists.get(n, (0.0, 0.0))
                hists[n] = (c + h.count, s + h.total)
    return counters, gauges, hists


def _escape_label_value(v) -> str:
    """Prometheus text format: backslash, double-quote and newline must
    be escaped inside label values (exposition_formats.md)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def render_prometheus() -> str:
    """Text exposition format, with full histogram exposition:
    cumulative ``_bucket{le="..."}`` lines per _Hist.BOUNDS bound plus
    ``le="+Inf"``, then ``_count``/``_sum``."""
    lines: List[str] = []

    def fmt_labels(labels: Tuple) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                         for k, v in labels)
        return "{" + inner + "}"

    with _lock:
        for (name, labels), h in _histograms.items():
            cum = 0
            for bound, n in zip(h.BOUNDS, h.buckets):
                cum += n
                le = fmt_labels(labels + (("le", f"{bound:g}"),))
                lines.append(f"{name}_bucket{le} {cum}")
            le = fmt_labels(labels + (("le", "+Inf"),))
            lines.append(f"{name}_bucket{le} {h.count}")
            lines.append(f"{name}_count{fmt_labels(labels)} {h.count}")
            lines.append(f"{name}_sum{fmt_labels(labels)} {h.total}")
        for (name, labels), v in _gauges.items():
            lines.append(f"{name}{fmt_labels(labels)} {v}")
        for (name, labels), v in _counters.items():
            lines.append(f"{name}{fmt_labels(labels)} {v}")
    return "\n".join(lines) + "\n"
