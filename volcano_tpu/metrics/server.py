"""Prometheus exposition endpoint (reference: the scheduler's /metrics on
--listen-address, cmd/scheduler/app/server.go:85)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import metrics as m


class MetricsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8080):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = m.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
