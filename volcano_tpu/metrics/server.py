"""Prometheus exposition + flight-recorder debug endpoints (reference:
the scheduler's /metrics on --listen-address, cmd/scheduler/app/
server.go:85).

Routes:
  /metrics           Prometheus text exposition
  /debug             index of the debug endpoints below
  /debug/cycles      ring-buffer summaries of the last N traced cycles
  /debug/trace       Chrome trace-event JSON for one cycle (?seq=N, default
                     the newest; load in chrome://tracing or Perfetto)
  /debug/pending     "why pending": per-job / per-reason unschedulable counts
  /debug/health      component health (cycle watchdog et al.); HTTP 503 when
                     any component reports degraded
  /debug/latency     pod lifecycle ledger: per-hop and e2e latency
                     percentiles, per-queue e2e, recent completions
  /debug/timeseries  last N cycles of key gauges/counters (metrics ring)

Unknown paths answer 404 with a JSON error body (never a bare status
line), like every other route.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import metrics as m


# the /debug index: route -> one-line description
DEBUG_ENDPOINTS = {
    "/debug/cycles": "ring-buffer summaries of the last N traced cycles",
    "/debug/trace": "Chrome trace-event JSON for one cycle (?seq=N)",
    "/debug/pending": "why-pending: per-job/per-reason unschedulable counts",
    "/debug/health": "component health (503 while degraded)",
    "/debug/latency": "pod lifecycle ledger: per-hop/e2e latency percentiles",
    "/debug/timeseries": "last N cycles of key gauges/counters",
    "/debug/serving": "serving hub shard depths / fan-out latency + "
                      "per-tenant admission counters",
    "/debug/explain": "placement decision provenance (?job=ns/name) + "
                      "pruning-readiness aggregates",
    "/debug/replication": "replica-set state: epoch, follower lag/applied "
                          "rvs, gap/bootstrap/fence counters, last audit",
    "/debug/durability": "write-ahead-log state: durable rv / lag, fsync "
                         "latency, segments, read-only degradation, last "
                         "recovery",
}


def _debug_response(path: str, query: dict):
    """(status, payload dict) for a /debug/* path, None for unknown."""
    from ..trace import tracer
    if path == "/debug":
        return 200, {"endpoints": DEBUG_ENDPOINTS}
    if path == "/debug/latency":
        from ..trace import ledger
        return 200, ledger.report()
    if path == "/debug/timeseries":
        from . import timeseries
        limit = query.get("limit")
        try:
            n = int(limit[0]) if limit else None
        except ValueError:
            return 400, {"error": f"bad limit {limit[0]!r}"}
        return 200, {"samples": timeseries.series(limit=n)}
    if path == "/debug/cycles":
        return 200, {"enabled": tracer.is_enabled(),
                     "cycles": [tracer.summary(r) for r in tracer.records()]}
    if path == "/debug/trace":
        seq = query.get("seq")
        if seq is not None:
            try:
                rec = tracer.get_record(int(seq[0]))
            except ValueError:
                return 400, {"error": f"bad seq {seq[0]!r}"}
        else:
            rec = tracer.last_record()
        if rec is None:
            return 404, {"error": "no traced cycle in the ring buffer",
                         "enabled": tracer.is_enabled()}
        return 200, tracer.chrome_trace(rec)
    if path == "/debug/health":
        report = m.health_report()
        # federation process mode: a member with no electable leader
        # (degraded — writes fail fast, reads are stale-annotated) is a
        # health component like any other and 503s the endpoint
        from ..replication import _ACTIVE
        member = _ACTIVE.get("member")
        if member is not None:
            role = member.role()
            report.setdefault("components", {})["replication_member"] = {
                "healthy": role != "degraded",
                "detail": f"role={role} "
                          f"lease={member.leader_hint().get('holder')}"}
            if role == "degraded":
                report["healthy"] = False
        return (200 if report["healthy"] else 503), report
    if path == "/debug/serving":
        from ..serving import serving_report
        return 200, serving_report()
    if path == "/debug/replication":
        from ..replication import replication_report
        return 200, replication_report()
    if path == "/debug/durability":
        from ..apiserver.wal import durability_report
        return 200, durability_report()
    if path == "/debug/explain":
        from ..trace import explain
        job = query.get("job")
        if job:
            rec = explain.job_record(job[0])
            if rec is None:
                return 404, {"error": "no explanation recorded for job "
                                      f"{job[0]!r}",
                             "enabled": explain.is_enabled()}
            return 200, rec
        limit = query.get("limit")
        try:
            n = int(limit[0]) if limit else 64
        except ValueError:
            return 400, {"error": f"bad limit {limit[0]!r}"}
        return 200, explain.report(limit=n)
    if path == "/debug/pending":
        report = tracer.pending_report()
        if report is None:
            return 200, {"enabled": tracer.is_enabled(), "pending_jobs": 0,
                         "reasons": {}, "jobs": {}}
        return 200, report
    return None


class MetricsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8080):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, status: int, body: bytes, ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlsplit(self.path)
                path = parsed.path.rstrip("/")
                if path == "/debug" or path.startswith("/debug/"):
                    res = _debug_response(
                        path, urllib.parse.parse_qs(parsed.query))
                    if res is not None:
                        status, payload = res
                        self._send(status, json.dumps(payload).encode(),
                                   "application/json")
                        return
                if path not in ("", "/metrics"):
                    # JSON error body like every other route (a bare 404
                    # status line broke piped `curl | jq` diagnostics)
                    self._send(404, json.dumps(
                        {"error": "not found", "path": path,
                         "endpoints": ["/metrics"]
                         + sorted(DEBUG_ENDPOINTS)}).encode(),
                        "application/json")
                    return
                self._send(200, m.render_prometheus().encode(),
                           "text/plain; version=0.0.4")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
