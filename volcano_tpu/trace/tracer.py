"""Cycle flight recorder: nested span tracing for the scheduling cycle.

Every scheduling cycle is recorded as a tree of spans — cycle →
open_session → snapshot → plugin opens → each action → solver context
build → kernel invocation → stage/finalize → close_session — with wall
time, counts (tasks considered, binds, victims) and outcome tags. The
last N cycles live in a ring buffer (default 64) and export as Chrome
trace-event JSON (chrome://tracing / Perfetto) or as compact per-cycle
summaries; the metrics server surfaces both under ``/debug/*``.

Designed to be LEFT ON in production: when disabled every ``span()``
call is one module-global check returning a shared null context; when
enabled a cycle creates a few dozen span objects (never one per task),
targeting <2% overhead on the steady-state cycle
(tests/test_trace.py::test_tracer_overhead).

Thread model: spans nest per-thread (the cycle runs on one thread); a
``span()`` on a thread with no open cycle is a no-op. Executor threads
record into the flight recorder through ``async_span`` (the bind flush),
which tags its spans with the cycle sequence they follow.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_perf = time.perf_counter

DEFAULT_CAPACITY = 64

_enabled = False
_lock = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
# spans from executor threads (bind flush), bucketed by the cycle seq
# they follow so per-cycle lookup is O(1); bounded independently of the
# ring (total spans, oldest cycle evicted first) so a burst can't grow
# it without limit
_async: Dict[int, List["Span"]] = {}
_async_count = 0
_ASYNC_SPAN_CAP = 4096
_seq = 0            # sequence of the cycle currently (or last) recording
_tls = threading.local()

# per-phase wall budgets in ms (docs/design/perf.md's budget rows); a
# cycle whose phase exceeds its budget is flagged in the summary and
# counted in volcano_trace_phase_over_budget_total
_budgets: Dict[str, float] = {}
DEFAULT_BUDGETS = {"cycle": 1000.0}

# latest "why pending" diagnosis (trace/pending.py), refreshed each
# cycle at session close while tracing is enabled
_pending_report: Optional[dict] = None

# root span of the cycle currently in flight (None between cycles) —
# read cross-thread by the cycle watchdog via live_phases()
_live_cycle: Optional["Span"] = None


class Span:
    __slots__ = ("name", "t0", "dur", "tags", "children")

    def __init__(self, name: str, t0: float):
        self.name = name
        self.t0 = t0
        self.dur = 0.0
        self.tags: Optional[dict] = None
        self.children: Optional[list] = None


class CycleRecord:
    __slots__ = ("seq", "wall_time", "root")

    def __init__(self, seq: int, wall_time: float, root: Span):
        self.seq = seq
        self.wall_time = wall_time
        self.root = root


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _SpanCtx:
    __slots__ = ("_span", "_stack")

    def __init__(self, span: Span, stack: list):
        self._span = span
        self._stack = stack

    def __enter__(self):
        return self._span

    def __exit__(self, *exc):
        s = self._span
        s.dur = _perf() - s.t0
        st = self._stack
        if st and st[-1] is s:
            st.pop()
        return False


class _CycleCtx:
    __slots__ = ("_root", "_seq")

    def __init__(self, root: Span, seq: int):
        self._root = root
        self._seq = seq

    def __enter__(self):
        return self._root

    def __exit__(self, *exc):
        global _live_cycle
        root = self._root
        root.dur = _perf() - root.t0
        _tls.stack = None
        if _live_cycle is root:
            _live_cycle = None
        _finish_cycle(root, self._seq)
        return False


class _AsyncCtx:
    __slots__ = ("_span", "_seq")

    def __init__(self, span: Span, seq: int):
        self._span = span
        self._seq = seq

    def __enter__(self):
        return self._span

    def __exit__(self, *exc):
        s = self._span
        s.dur = _perf() - s.t0
        _tls.astack = None
        global _async_count
        with _lock:
            _async.setdefault(self._seq, []).append(s)
            _async_count += 1
            while _async_count > _ASYNC_SPAN_CAP and len(_async) > 1:
                _async_count -= len(_async.pop(next(iter(_async))))
        return False


class _AsyncChildCtx:
    """A nested async span: child of the thread's innermost open async
    span (NOT a new _async root — flush-wide aggregates like summary()'s
    bind_flush_ms sum roots only, so sub-phases never double-count)."""

    __slots__ = ("_span", "_stack")

    def __init__(self, span: Span, stack: list):
        self._span = span
        self._stack = stack

    def __enter__(self):
        return self._span

    def __exit__(self, *exc):
        s = self._span
        s.dur = _perf() - s.t0
        st = self._stack
        if st and st[-1] is s:
            st.pop()
        return False


# -- control ----------------------------------------------------------------


def enable(capacity: Optional[int] = None) -> None:
    """Turn the flight recorder on (idempotent). The pod lifecycle
    ledger (trace/ledger.py) rides the same switch: one production
    toggle covers both, and the <2% overhead gate measures both."""
    global _enabled
    if capacity is not None:
        configure(capacity=capacity)
    _enabled = True
    from . import ledger
    ledger.enable()


def disable() -> None:
    global _enabled
    _enabled = False
    _tls.stack = None
    _tls.astack = None
    from . import ledger
    ledger.disable()


def is_enabled() -> bool:
    return _enabled


def configure(capacity: int) -> None:
    """Resize the ring buffer, keeping the newest records."""
    global _ring
    capacity = max(1, int(capacity))
    with _lock:
        if _ring.maxlen != capacity:
            _ring = deque(_ring, maxlen=capacity)


def reset() -> None:
    """Drop all recorded cycles (tests)."""
    global _pending_report, _async_count
    with _lock:
        _ring.clear()
        _async.clear()
        _async_count = 0
    _pending_report = None
    _tls.stack = None
    _tls.astack = None


def set_budgets(budgets: Dict[str, float]) -> None:
    """Replace the per-phase wall budgets ({span name: ms})."""
    global _budgets
    _budgets = dict(budgets)


def budgets() -> Dict[str, float]:
    return dict(_budgets)


def env_capacity() -> Optional[int]:
    """VOLCANO_TRACE_CAPACITY as an int, or None when unset or malformed
    (a bad value for an optional diagnostics knob must not kill the
    scheduler at startup)."""
    cap = os.environ.get("VOLCANO_TRACE_CAPACITY")
    if not cap:
        return None
    try:
        return int(cap)
    except ValueError:
        import logging
        logging.getLogger(__name__).warning(
            "ignoring malformed VOLCANO_TRACE_CAPACITY=%r", cap)
        return None


def enable_from_env() -> bool:
    """Honor VOLCANO_TRACE / VOLCANO_TRACE_CAPACITY (entry points call
    this once at startup); returns whether tracing ended up enabled."""
    if os.environ.get("VOLCANO_TRACE", "").lower() in ("1", "true", "yes"):
        enable(capacity=env_capacity())
    return _enabled


# -- recording --------------------------------------------------------------


def cycle(**tags):
    """Open the root span of one scheduling cycle on this thread."""
    global _seq, _live_cycle
    if not _enabled:
        return _NULL
    root = Span("cycle", _perf())
    if tags:
        root.tags = tags
    with _lock:
        _seq += 1
        seq = _seq
    _tls.stack = [root]
    _live_cycle = root
    return _CycleCtx(root, seq)


def span(name: str, **tags):
    """A nested span under the innermost open span of this thread's
    cycle; a no-op context when tracing is off or no cycle is open."""
    if not _enabled:
        return _NULL
    stack = getattr(_tls, "stack", None)
    if not stack:
        return _NULL
    s = Span(name, _perf())
    if tags:
        s.tags = tags
    parent = stack[-1]
    if parent.children is None:
        parent.children = []
    parent.children.append(s)
    stack.append(s)
    return _SpanCtx(s, stack)


def add_tags(**tags) -> None:
    """Merge tags into the innermost open span (for counts known only
    mid-span: tasks considered, binds, victims)."""
    if not _enabled:
        return
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    s = stack[-1]
    if s.tags is None:
        s.tags = tags
    else:
        s.tags.update(tags)


def tag_cycle(**tags) -> None:
    """Merge tags into the cycle's root span from anywhere inside it."""
    if not _enabled:
        return
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    root = stack[0]
    if root.tags is None:
        root.tags = tags
    else:
        root.tags.update(tags)


def async_span(name: str, **tags):
    """A span recorded from a non-cycle thread (the bind-flush executor),
    attached to the newest cycle's sequence number. Nests per-thread: an
    async_span opened inside another (the flush's store pass opening its
    echo-ingest sub-phase) becomes a CHILD of the open one rather than a
    second root, so per-cycle flush totals never double-count."""
    if not _enabled:
        return _NULL
    s = Span(name, _perf())
    if tags:
        s.tags = tags
    stack = getattr(_tls, "astack", None)
    if stack:
        parent = stack[-1]
        if parent.children is None:
            parent.children = []
        parent.children.append(s)
        stack.append(s)
        return _AsyncChildCtx(s, stack)
    _tls.astack = [s]
    return _AsyncCtx(s, _seq)


def _finish_cycle(root: Span, seq: int) -> None:
    rec = CycleRecord(seq, time.time(), root)   # lint: allow(clock-discipline): Chrome trace-export wall timestamp — presentation metadata; no fingerprint or decision reads it
    with _lock:
        _ring.append(rec)
    budget = _budgets or DEFAULT_BUDGETS
    if budget:
        over = _over_budget(rec, budget)
        if over:
            from ..metrics import metrics as m
            for phase in over:
                m.inc(f"{m.NS}_trace_phase_over_budget_total", phase=phase)


def current_seq() -> int:
    """Sequence number of the cycle currently (or last) recording —
    joinable against /debug/trace?seq= and /debug/cycles entries."""
    return _seq


def live_phases() -> Dict[str, dict]:
    """Phase breakdown of the cycle currently IN FLIGHT — the cycle
    watchdog's view of a stuck ``run_once`` (a completed cycle's record
    comes from the ring buffer instead). Top-level child spans of the
    live root, name -> {ms, count, open}; an open span (dur not yet
    written) reports its elapsed wall time so far. Reads deliberately
    race the recording thread: children lists are append-only and spans
    are never removed, so a snapshot is always structurally sound —
    durations of spans closing mid-read may be a frame stale."""
    root = _live_cycle
    if root is None:
        return {}
    now = _perf()
    out: Dict[str, dict] = {}
    total = now - root.t0
    for s in list(root.children or ()):
        is_open = s.dur == 0.0
        ms = ((now - s.t0) if is_open else s.dur) * 1000.0
        ent = out.setdefault(s.name, {"ms": 0.0, "count": 0, "open": False})
        ent["ms"] = round(ent["ms"] + ms, 3)
        ent["count"] += 1
        ent["open"] = ent["open"] or is_open
    out["cycle"] = {"ms": round(total * 1000.0, 3), "count": 1,
                    "open": True}
    return out


def set_pending_report(report: Optional[dict]) -> None:
    global _pending_report
    _pending_report = report


def pending_report() -> Optional[dict]:
    return _pending_report


# -- reading ----------------------------------------------------------------


def records() -> List[CycleRecord]:
    """Snapshot of the ring buffer, oldest first."""
    with _lock:
        return list(_ring)


def last_record() -> Optional[CycleRecord]:
    with _lock:
        return _ring[-1] if _ring else None


def get_record(seq: int) -> Optional[CycleRecord]:
    with _lock:
        for rec in _ring:
            if rec.seq == seq:
                return rec
    return None


def _async_spans_for(seq: int) -> List[Span]:
    with _lock:
        return list(_async.get(seq, ()))


# -- exports ----------------------------------------------------------------


def chrome_trace(rec: CycleRecord) -> dict:
    """Chrome trace-event JSON (load in chrome://tracing or Perfetto):
    complete ('X') events, ts/dur in microseconds relative to cycle
    start; the async bind-flush spans ride a second tid."""
    events: List[dict] = []
    base = rec.root.t0

    def emit(s: Span, tid: int) -> None:
        ev = {"name": s.name, "ph": "X", "pid": 1, "tid": tid,
              "ts": round((s.t0 - base) * 1e6, 3),
              "dur": round(s.dur * 1e6, 3)}
        if s.tags:
            ev["args"] = dict(s.tags)
        events.append(ev)
        for c in s.children or ():
            emit(c, tid)

    emit(rec.root, 1)
    for s in _async_spans_for(rec.seq):
        emit(s, 2)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"cycle_seq": rec.seq, "wall_time": rec.wall_time}}


def flat_phases(rec: CycleRecord) -> Dict[str, dict]:
    """'/'-joined span paths -> {ms, count}, aggregated over the tree
    (the per-phase breakdown behind bench.py --trace and the phase-timer
    table)."""
    out: Dict[str, dict] = {}

    def walk(s: Span, prefix: str) -> None:
        path = f"{prefix}/{s.name}" if prefix else s.name
        e = out.get(path)
        if e is None:
            out[path] = e = {"ms": 0.0, "count": 0}
        e["ms"] += s.dur * 1000.0
        e["count"] += 1
        for c in s.children or ():
            walk(c, path)

    for c in rec.root.children or ():
        walk(c, "")
    for e in out.values():
        e["ms"] = round(e["ms"], 3)
    return out


def async_phases(rec: CycleRecord) -> Dict[str, dict]:
    """'/'-joined span paths -> {ms, count} over the cycle's ASYNC spans
    (the bind flush that follows it): the flat_phases twin for the
    executor side, behind bench.py's flush sub-phase attribution
    (bind_flush.apply / bind_flush.store / bind_flush.store/bind_flush.echo)."""
    out: Dict[str, dict] = {}

    def walk(s: Span, prefix: str) -> None:
        path = f"{prefix}/{s.name}" if prefix else s.name
        e = out.get(path)
        if e is None:
            out[path] = e = {"ms": 0.0, "count": 0}
        e["ms"] += s.dur * 1000.0
        e["count"] += 1
        for c in s.children or ():
            walk(c, path)

    for s in _async_spans_for(rec.seq):
        walk(s, "")
    for e in out.values():
        e["ms"] = round(e["ms"], 3)
    return out


def _span_count(s: Span) -> int:
    return 1 + sum(_span_count(c) for c in s.children or ())


def _over_budget(rec: CycleRecord, budget: Dict[str, float]) -> List[str]:
    over = []
    cycle_budget = budget.get("cycle")
    if cycle_budget is not None and rec.root.dur * 1000.0 > cycle_budget:
        over.append("cycle")

    def walk(s: Span) -> None:
        b = budget.get(s.name)
        if b is not None and s.dur * 1000.0 > b:
            over.append(s.name)
        for c in s.children or ():
            walk(c)

    for c in rec.root.children or ():
        walk(c)
    return over


def summary(rec: CycleRecord) -> dict:
    """Compact per-cycle record for /debug/cycles: wall time, top-level
    phase breakdown, attribution coverage, tags, budget verdicts."""
    cycle_ms = rec.root.dur * 1000.0
    phases: Dict[str, dict] = {}
    covered = 0.0
    for c in rec.root.children or ():
        e = phases.get(c.name)
        if e is None:
            phases[c.name] = e = {"ms": 0.0, "count": 0}
        e["ms"] += c.dur * 1000.0
        e["count"] += 1
        covered += c.dur * 1000.0
    for e in phases.values():
        e["ms"] = round(e["ms"], 3)
    budget = _budgets or DEFAULT_BUDGETS
    flush_ms = sum(s.dur for s in _async_spans_for(rec.seq)) * 1000.0
    out = {"seq": rec.seq, "wall_time": rec.wall_time,
           "cycle_ms": round(cycle_ms, 3),
           "covered_ms": round(covered, 3),
           "coverage": round(covered / cycle_ms, 4) if cycle_ms > 0 else 1.0,
           "spans": _span_count(rec.root),
           "phases": phases,
           "tags": dict(rec.root.tags) if rec.root.tags else {},
           "over_budget": _over_budget(rec, budget)}
    if flush_ms:
        out["bind_flush_ms"] = round(flush_ms, 3)
    return out


def validate_chrome_trace(obj: dict) -> None:
    """Assert ``obj`` is a well-formed Chrome trace-event export of one
    cycle (the span schema behind `make trace-smoke`); raises ValueError
    on the first violation."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError("missing traceEvents")
    events = obj["traceEvents"]
    if not isinstance(events, list) or not events:
        raise ValueError("traceEvents must be a non-empty list")
    roots = 0
    for ev in events:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event missing {key!r}: {ev}")
        if ev["ph"] != "X":
            raise ValueError(f"expected complete ('X') events, got {ev['ph']!r}")
        if not isinstance(ev["name"], str) or not ev["name"]:
            raise ValueError("event name must be a non-empty string")
        for key in ("ts", "dur"):
            if not isinstance(ev[key], (int, float)) or ev[key] < 0:
                raise ValueError(f"event {key} must be a non-negative number")
        args = ev.get("args")
        if args is not None and not isinstance(args, dict):
            raise ValueError("event args must be a dict")
        if ev["name"] == "cycle" and ev["tid"] == 1:
            roots += 1
    if roots != 1:
        raise ValueError(f"expected exactly one cycle root, got {roots}")
