"""Pod lifecycle ledger: per-pod transition timestamps and latency hops.

The flight recorder (tracer.py) answers "where did this CYCLE spend its
time"; this module answers the question a control plane serving live
traffic is judged on: "how long did this POD take from submission to
confirmed bind, and which hop ate it?" Every schedulable pod gets one
ledger entry stamped with monotonic transition timestamps as it flows
through the cache, the actions and the sharded bind flush:

    submitted          watch ingest of a pending, responsible pod
    enqueued           its PodGroup gated Pending -> Inqueue (enqueue
                       action; skipped when the group arrives Inqueue)
    session_eligible   first cycle the pod entered the allocate batch
    kernel_placed      the placement kernel assigned it a node
    bind_staged        the cache recorded its bind for the flush
    store_committed    the store write landed (binder pass succeeded)
    echo_confirmed     the bind's watch echo re-ingested into the cache
                       (terminal: the hop/e2e aggregates absorb the entry)

plus *detour* counters that never advance the chain: ``retry`` (a bind
failure entered backoff), ``quarantined`` (retry budget exhausted),
``healed`` (gang-atomic unbind of a bound sibling). Stages stamp ONCE —
a pod re-placed after a retry keeps its original timestamps, so the
bind_staged->store_committed hop absorbs the whole retry window, which
is exactly the attribution an operator wants.

Hop latencies are computed between consecutive *present* stamps (a
skipped stage — e.g. ``enqueued`` for a group created Inqueue — skips
its hop), so per-hop sums always equal the e2e latency
(tests/test_lifecycle.py holds that identity).

All timestamps come from the caller (the store's clock), so a simulator
on a virtual clock produces bit-identical aggregates across double runs
(``fingerprint()``); the live scheduler stamps wall time. Aggregates
export as ``volcano_pod_e2e_latency_milliseconds{queue}`` /
``volcano_pod_hop_latency_milliseconds{hop}`` histograms and the
``/debug/latency`` endpoint serves p50/p95/p99 over a bounded sample
window. Enabled/disabled together with the tracer (one production
switch); a disabled ledger's ``stamp`` is one flag check.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from typing import Dict, List, Optional

STAGES = ("submitted", "enqueued", "session_eligible", "kernel_placed",
          "bind_staged", "store_committed", "echo_confirmed")
_STAGE_IDX = {s: i for i, s in enumerate(STAGES)}
DETOURS = ("retry", "quarantined", "healed")

# interned hop names, indexed [from_idx][to_idx] — a 50k-bind flush
# completes 50k entries and building "a->b" strings per completion was
# a measurable slice of the commit path (tools/flush_bench.py --profile)
_HOP_NAME = [[f"{a}->{b}" for b in STAGES] for a in STAGES]
_COMMIT_IDX = _STAGE_IDX["store_committed"]
_ECHO_IDX = _STAGE_IDX["echo_confirmed"]

# /debug/latency percentile window per hop (deterministic: the LAST N
# completions, not a randomized reservoir)
SAMPLE_WINDOW = 1024
# completed-bind ring for /debug/latency's recent view (key, trace, e2e)
RECENT_CAPACITY = 64

_enabled = False
_lock = threading.Lock()


class _Entry:
    __slots__ = ("stamps", "detours", "trace", "queue", "job")

    def __init__(self):
        self.stamps: List[tuple] = []       # [(stage_idx, t)] ascending
        self.detours: Optional[dict] = None
        self.trace: Optional[str] = None
        self.queue: Optional[str] = None
        self.job: Optional[str] = None


class _Agg:
    __slots__ = ("count", "total", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.samples: deque = deque(maxlen=SAMPLE_WINDOW)

    def add(self, ms: float) -> None:
        self.count += 1
        self.total += ms
        self.samples.append(ms)

    def percentiles(self) -> dict:
        if not self.samples:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        import math
        s = sorted(self.samples)
        n = len(s)
        # nearest-rank: index ceil(q*n) - 1 (int(q*n) alone reads one
        # rank high — p50 of two samples must be the first); the round
        # guards float fuzz like 0.95*20 == 19.000000000000004
        at = lambda q: s[min(n - 1, max(0, math.ceil(round(q * n, 9))
                                        - 1))]
        return {"p50": round(at(0.50), 3), "p95": round(at(0.95), 3),
                "p99": round(at(0.99), 3)}

    def report(self) -> dict:
        out = {"count": self.count,
               "mean_ms": round(self.total / self.count, 3)
               if self.count else 0.0}
        out.update(self.percentiles())
        return out


_entries: Dict[str, _Entry] = {}
_hops: Dict[str, _Agg] = {}          # "submitted->enqueued", ..., "e2e"
_queue_e2e: Dict[str, _Agg] = {}     # queue name -> e2e agg
_detour_totals: Dict[str, int] = {}
# completion ring: raw (key, trace, queue, e2e_ms, stamps, detours)
# tuples, FORMATTED lazily by report() — only the surviving
# RECENT_CAPACITY entries ever pay the dict/round work, not all 50k
# completions of a flush
_recent: deque = deque(maxlen=RECENT_CAPACITY)
_completed = 0
_dropped = 0
# prometheus exports staged by completions under _lock, drained to
# metrics.observe_bulk AFTER release by the public entry points: one
# metrics-lock pass per (metric, label) per delivery instead of ~6 per
# completed pod (a 50k-bind flush echo otherwise pays ~300k lock
# acquisitions on the executor thread)
_pending_exports: Dict[tuple, list] = {}
# staged-export key tuples, interned per (metric, label) — rebuilt
# per completion they were another per-pod allocation
_export_keys: Dict[tuple, tuple] = {}
_metrics_mod = None


def _metrics():
    """The metrics module, imported once (the per-completion
    ``from ..metrics import metrics`` showed up in flush profiles)."""
    global _metrics_mod
    if _metrics_mod is None:
        from ..metrics import metrics as m
        _metrics_mod = m
    return _metrics_mod


# native completion switch — module attr so the native-vs-Python parity
# tests can force either engine
NATIVE_CONFIRM = True
_native = None
_native_tried = False


def _ledger_native():
    """The fastmodel C completion pass (None = Python loop). Registered
    lazily with this module's _Entry/_Agg layouts and hop table."""
    global _native, _native_tried
    if not _native_tried:
        _native_tried = True
        try:
            from ..native.build import fastmodel
            fm = fastmodel()
            if fm is not None and hasattr(fm, "ledger_confirm_runs"):
                fm.register_ledger_types(_Entry, _Agg, _HOP_NAME,
                                         _COMMIT_IDX, _ECHO_IDX)
                _native = fm
        except Exception:
            _native = None
    return _native


# -- control ----------------------------------------------------------------


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    global _completed, _dropped
    with _lock:
        _entries.clear()
        _hops.clear()
        _queue_e2e.clear()
        _detour_totals.clear()
        _recent.clear()
        _pending_exports.clear()
        _export_keys.clear()
        _completed = 0
        _dropped = 0


def _drain_exports() -> None:
    """Push staged histogram observations out (called by every public
    stamping entry point after releasing the ledger lock)."""
    if not _pending_exports:
        return
    with _lock:
        if not _pending_exports:
            return
        staged = dict(_pending_exports)
        _pending_exports.clear()
    m = _metrics()
    for (name, labels), values in staged.items():
        m.observe_bulk(name, values, **dict(labels))


# -- stamping ---------------------------------------------------------------


def _stamp_locked(key: str, idx: int, now: float, queue, job, trace) -> None:
    e = _entries.get(key)
    if e is None:
        # ONLY the "submitted" stamp creates entries: a late stamp for a
        # pod whose entry already completed (the in-process store echoes
        # synchronously, so a store_committed stamp can arrive after the
        # echo confirmed and absorbed the entry) must never resurrect it
        # as a phantom open entry.
        if idx != 0:
            return
        e = _entries[key] = _Entry()
    if queue is not None:
        e.queue = queue
    if job is not None:
        e.job = job
    if trace is not None:
        e.trace = trace
    stamps = e.stamps
    if stamps:
        last_i, last_t = stamps[-1]
        # stamp indexes are strictly ascending, so "already stamped" and
        # "earlier than the newest stage" (a replay — restart relist,
        # duplicate echo) collapse to one compare
        if idx <= last_i:
            return
        if now < last_t:
            now = last_t   # clamp: hops are never negative
    stamps.append((idx, now))
    if idx == _ECHO_IDX:
        _complete_locked(key, e)


def stamp(key: str, stage: str, now: float, queue: Optional[str] = None,
          job: Optional[str] = None, trace: Optional[str] = None) -> None:
    """Record ``stage`` for pod ``key`` at time ``now`` (set-once)."""
    if not _enabled:
        return
    idx = _STAGE_IDX[stage]
    with _lock:
        _stamp_locked(key, idx, now, queue, job, trace)
    _drain_exports()


def stamp_bulk(keys, stage: str, now: float, trace: Optional[str] = None,
               queue: Optional[str] = None) -> None:
    """One lock pass for a batch point (the allocate batch, a flush's
    committed list, a shard's echo delivery)."""
    if not _enabled:
        return
    idx = _STAGE_IDX[stage]
    with _lock:
        for key in keys:
            _stamp_locked(key, idx, now, queue, None, trace)
    _drain_exports()


def stamp_runs(runs, stage: str, trace: Optional[str] = None) -> None:
    """``stamp_bulk`` for several key batches with DIFFERENT timestamps
    in one lock pass — ``runs = [(keys, t)]``. The coalesced bind drain
    stamps every burst's ``bind_staged`` (each with its own foreground
    staging instant) through ONE ledger call per flush instead of one
    per gang."""
    if not _enabled:
        return
    idx = _STAGE_IDX[stage]
    complete = idx == _ECHO_IDX
    with _lock:
        for keys, t in runs:
            for key in keys:
                e = _entries.get(key)
                if e is None:
                    continue   # only "submitted" creates entries
                if trace is not None:
                    e.trace = trace
                stamps = e.stamps
                if stamps:
                    last_i, last_t = stamps[-1]
                    if idx <= last_i:
                        continue
                    stamps.append((idx, t if t >= last_t else last_t))
                else:
                    stamps.append((idx, t))
                if complete:
                    _complete_locked(key, e)
    _drain_exports()


def _confirm_one_locked(key: str, queue, commit_t: float,
                        echo_t: float) -> None:
    """Stamp ``store_committed`` @commit_t then ``echo_confirmed``
    @echo_t on one entry — the flat form of two ``_stamp_locked`` calls,
    specialized for the bind-echo hot path (one dict probe, no per-stage
    re-validation)."""
    e = _entries.get(key)
    if e is None:
        return   # completed/dropped already, or never submitted
    if queue is not None:
        e.queue = queue
    stamps = e.stamps
    last_i, last_t = stamps[-1] if stamps else (-1, 0.0)
    if last_i >= _ECHO_IDX:
        return
    if last_i < _COMMIT_IDX:
        t = commit_t if commit_t >= last_t else last_t
        stamps.append((_COMMIT_IDX, t))
        last_t = t
    t = echo_t if echo_t >= last_t else last_t
    stamps.append((_ECHO_IDX, t))
    _complete_locked(key, e)


def confirm(key: str, now: float, queue: Optional[str] = None,
            commit_t: Optional[float] = None) -> None:
    """Bind-echo ingest: stamp ``store_committed`` then
    ``echo_confirmed`` in one lock pass. ``commit_t`` (default ``now``)
    is the instant the owning shard PUBLISHED to the store, so the
    ``store_committed->echo_confirmed`` hop measures the echo pipeline's
    internal queue wait instead of folding into staged->committed. With
    no commit_t the two stamps coincide (a zero hop); a remote mirror's
    delayed echo leaves the earlier write-time store_committed stamp in
    place (set-once) and the hop measures the real propagation delay."""
    if not _enabled:
        return
    with _lock:
        _confirm_one_locked(key, queue, commit_t if commit_t is not None
                            else now, now)
    _drain_exports()


def confirm_bulk(items, now: float, commit_t: Optional[float] = None) -> None:
    """``confirm`` for a whole echo delivery: items = [(key, queue)]."""
    if not _enabled:
        return
    ct = commit_t if commit_t is not None else now
    with _lock:
        for key, queue in items:
            _confirm_one_locked(key, queue, ct, now)
    _drain_exports()


def confirm_runs(runs, now: float, commit_t: Optional[float] = None) -> None:
    """``confirm`` for a whole echo delivery grouped into per-job runs —
    ``runs = [(keys, queue)]``, ONE ledger call per delivery with one
    queue lookup per run instead of one (key, queue) pair per pod (the
    native echo pass hands its run segments straight here).

    This is the commit path's hottest ledger loop (50k completions per
    flush), so the per-run invariants — queue aggregate, e2e export
    list, the per-hop aggregate/export resolution — are hoisted out of
    the per-pod body, and the entry completion is inlined for the
    common shape (no out-of-order stamps). Aggregation arithmetic is
    IDENTICAL to :func:`_complete_locked` — fingerprints must not see
    which loop ran."""
    if not _enabled:
        return
    global _completed
    ct = commit_t if commit_t is not None else now
    m = _metrics()
    fm = _ledger_native() if NATIVE_CONFIRM else None
    if fm is not None:
        try:
            with _lock:
                _completed += fm.ledger_confirm_runs(
                    _entries, _hops, _queue_e2e, _pending_exports,
                    _export_keys, _recent, m.POD_HOP_LATENCY,
                    m.POD_E2E_LATENCY, runs, ct, float(now))
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "native ledger completion failed; Python fallback")
            # fall through: fully completed entries already left
            # _entries and the loop below finishes the rest. The C pass
            # can only fail on memory exhaustion (its hop-sink table
            # exceeds the theoretical hop-name count), so a torn entry
            # — aggregated but not retired — is an OOM-only artifact.
        else:
            _drain_exports()
            return
    with _lock:
        hop_cache: dict = {}

        def hop_sinks(hop):
            agg = _hops.get(hop)
            if agg is None:
                agg = _hops[hop] = _Agg()
            ek = _export_keys.get(hop)
            if ek is None:
                ek = _export_keys[hop] = (m.POD_HOP_LATENCY,
                                          (("hop", hop),))
            lst = _pending_exports.get(ek)
            if lst is None:
                lst = _pending_exports[ek] = []
            sinks = hop_cache[hop] = (agg, agg.samples, lst)
            return sinks

        e2e_agg = _hops.get("e2e")
        if e2e_agg is None:
            e2e_agg = _hops["e2e"] = _Agg()
        for keys, queue in runs:
            q = queue or ""
            qagg = _queue_e2e.get(q)
            if qagg is None:
                qagg = _queue_e2e[q] = _Agg()
            ek = _export_keys.get(("q", q))
            if ek is None:
                ek = _export_keys[("q", q)] = (m.POD_E2E_LATENCY,
                                               (("queue", q),))
            q_exports = _pending_exports.get(ek)
            if q_exports is None:
                q_exports = _pending_exports[ek] = []
            for key in keys:
                e = _entries.get(key)
                if e is None:
                    continue
                stamps = e.stamps
                last_i, last_t = stamps[-1] if stamps else (-1, 0.0)
                if last_i >= _ECHO_IDX:
                    continue
                if queue is not None:
                    e.queue = queue
                if last_i < _COMMIT_IDX:
                    t = ct if ct >= last_t else last_t
                    stamps.append((_COMMIT_IDX, t))
                    last_t = t
                stamps.append((_ECHO_IDX,
                               now if now >= last_t else last_t))
                # inline completion (the _complete_locked body with the
                # per-run lookups above already resolved)
                del _entries[key]
                _completed += 1
                e2e_ms = (stamps[-1][1] - stamps[0][1]) * 1000.0
                hop_list: list = []
                prev_i, prev_t = stamps[0]
                for i1, t1 in stamps[1:]:
                    hop = _HOP_NAME[prev_i][i1]
                    ms = (t1 - prev_t) * 1000.0
                    prev_i, prev_t = i1, t1
                    hop_list.append((hop, ms))
                    sinks = hop_cache.get(hop)
                    if sinks is None:
                        sinks = hop_sinks(hop)
                    agg, samples, exports = sinks
                    agg.count += 1
                    agg.total += ms
                    samples.append(ms)
                    exports.append(ms)
                e2e_agg.count += 1
                e2e_agg.total += e2e_ms
                e2e_agg.samples.append(e2e_ms)
                qagg.count += 1
                qagg.total += e2e_ms
                qagg.samples.append(e2e_ms)
                q_exports.append(e2e_ms)
                _recent.append((key, e.trace, q, e2e_ms, hop_list,
                                e.detours))
    _drain_exports()


def detour(key: str, kind: str) -> None:
    """Count a retry/quarantined/healed detour on the pod's entry (a
    no-op for pods the ledger never saw submitted)."""
    if not _enabled:
        return
    with _lock:
        e = _entries.get(key)
        if e is None:
            return
        if e.detours is None:
            e.detours = {}
        e.detours[kind] = e.detours.get(kind, 0) + 1
        _detour_totals[kind] = _detour_totals.get(kind, 0) + 1


def reopen(key: str, kind: str, now: float) -> None:
    """A CONFIRMED bind was reverted (gang-atomic heal unbinding a bound
    sibling whose echo already completed its entry): count the detour
    unconditionally and restart the pod's lifecycle — a fresh entry
    re-submitted at the heal instant — so its eventual re-placement is
    tracked instead of every later stamp being dropped on the floor. An
    entry still OPEN (the remote-store shape, where the heal can run
    before the echo) just takes the detour; its original stamps stand
    and the staged->committed hop absorbs the heal window."""
    if not _enabled:
        return
    with _lock:
        _detour_totals[kind] = _detour_totals.get(kind, 0) + 1
        e = _entries.get(key)
        if e is None:
            e = _entries[key] = _Entry()
            e.stamps.append((0, now))
        if e.detours is None:
            e.detours = {}
        e.detours[kind] = e.detours.get(kind, 0) + 1


def drop(key: str) -> None:
    """The pod was deleted before confirmation: retire its entry so it
    can never show up as an orphan."""
    if not _enabled:
        return
    global _dropped
    with _lock:
        if _entries.pop(key, None) is not None:
            _dropped += 1


def _complete_locked(key: str, e: _Entry) -> None:
    global _completed
    del _entries[key]
    _completed += 1
    m = _metrics()
    stamps = e.stamps
    e2e_ms = (stamps[-1][1] - stamps[0][1]) * 1000.0
    hop_list: list = []   # stamp idxs are strictly ascending: no dup keys
    prev_i, prev_t = stamps[0]
    for i1, t1 in stamps[1:]:
        hop = _HOP_NAME[prev_i][i1]
        ms = (t1 - prev_t) * 1000.0
        prev_i, prev_t = i1, t1
        hop_list.append((hop, ms))
        agg = _hops.get(hop)
        if agg is None:
            agg = _hops[hop] = _Agg()
        agg.add(ms)
        # prometheus export rides the completion (staged here under
        # _lock with an interned key tuple, drained in bulk by the
        # public entry point that triggered it)
        ek = _export_keys.get(hop)
        if ek is None:
            ek = _export_keys[hop] = (m.POD_HOP_LATENCY, (("hop", hop),))
        lst = _pending_exports.get(ek)
        if lst is None:
            lst = _pending_exports[ek] = []
        lst.append(ms)
    agg = _hops.get("e2e")
    if agg is None:
        agg = _hops["e2e"] = _Agg()
    agg.add(e2e_ms)
    q = e.queue or ""
    qagg = _queue_e2e.get(q)
    if qagg is None:
        qagg = _queue_e2e[q] = _Agg()
    qagg.add(e2e_ms)
    _recent.append((key, e.trace, q, e2e_ms, hop_list,
                    e.detours))   # formatted lazily by report()
    ek = _export_keys.get(("q", q))
    if ek is None:
        ek = _export_keys[("q", q)] = (m.POD_E2E_LATENCY, (("queue", q),))
    lst = _pending_exports.get(ek)
    if lst is None:
        lst = _pending_exports[ek] = []
    lst.append(e2e_ms)


# -- reading ----------------------------------------------------------------


def trace_of(key: str) -> Optional[str]:
    """The correlation ID recorded on a pod's OPEN ledger entry (completed
    binds surface theirs in ``report()['recent']``)."""
    with _lock:
        e = _entries.get(key)
        return e.trace if e is not None else None


def stats() -> dict:
    with _lock:
        return {"enabled": _enabled, "open": len(_entries),
                "completed": _completed, "dropped": _dropped,
                "detours": dict(_detour_totals)}


def orphans(store) -> List[str]:
    """Open entries whose pod no longer exists in the store — a stamp
    path that forgot to ``drop()`` on delete shows up here (the
    obs-smoke gate requires zero)."""
    with _lock:
        keys = list(_entries)
    out = []
    for key in keys:
        ns, _, name = key.partition("/")
        if store.get("pods", name, ns) is None:
            out.append(key)
    return out


def report() -> dict:
    """The ``/debug/latency`` payload: per-hop and e2e percentiles,
    per-queue e2e, detour totals, open/completed counts and the recent
    completion ring (pod -> trace id join)."""
    with _lock:
        return {
            "enabled": _enabled,
            "open": len(_entries),
            "completed": _completed,
            "dropped": _dropped,
            "detours": dict(_detour_totals),
            "hops": {hop: agg.report() for hop, agg in sorted(_hops.items())},
            "per_queue_e2e": {q: agg.report()
                              for q, agg in sorted(_queue_e2e.items())},
            "recent": [
                {"pod": key, "trace": trace, "queue": q,
                 "e2e_ms": round(e2e_ms, 3),
                 "hops": {h: round(ms, 3) for h, ms in hop_list},
                 "detours": dict(detours) if detours else {}}
                for key, trace, q, e2e_ms, hop_list, detours in _recent],
        }


def fingerprint() -> str:
    """Deterministic digest of the aggregate state — two virtual-clock
    sim runs from one seed must produce identical ledgers (the obs-smoke
    double-run gate)."""
    h = hashlib.sha256()
    with _lock:
        h.update(f"completed={_completed} dropped={_dropped}\n".encode())
        for kind in sorted(_detour_totals):
            h.update(f"detour {kind}={_detour_totals[kind]}\n".encode())
        for hop in sorted(_hops):
            agg = _hops[hop]
            h.update(f"hop {hop} n={agg.count} "
                     f"sum={agg.total:.9f}\n".encode())
        for q in sorted(_queue_e2e):
            agg = _queue_e2e[q]
            h.update(f"queue {q} n={agg.count} "
                     f"sum={agg.total:.9f}\n".encode())
    return h.hexdigest()
