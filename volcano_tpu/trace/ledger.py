"""Pod lifecycle ledger: per-pod transition timestamps and latency hops.

The flight recorder (tracer.py) answers "where did this CYCLE spend its
time"; this module answers the question a control plane serving live
traffic is judged on: "how long did this POD take from submission to
confirmed bind, and which hop ate it?" Every schedulable pod gets one
ledger entry stamped with monotonic transition timestamps as it flows
through the cache, the actions and the sharded bind flush:

    submitted          watch ingest of a pending, responsible pod
    enqueued           its PodGroup gated Pending -> Inqueue (enqueue
                       action; skipped when the group arrives Inqueue)
    session_eligible   first cycle the pod entered the allocate batch
    kernel_placed      the placement kernel assigned it a node
    bind_staged        the cache recorded its bind for the flush
    store_committed    the store write landed (binder pass succeeded)
    echo_confirmed     the bind's watch echo re-ingested into the cache
                       (terminal: the hop/e2e aggregates absorb the entry)

plus *detour* counters that never advance the chain: ``retry`` (a bind
failure entered backoff), ``quarantined`` (retry budget exhausted),
``healed`` (gang-atomic unbind of a bound sibling). Stages stamp ONCE —
a pod re-placed after a retry keeps its original timestamps, so the
bind_staged->store_committed hop absorbs the whole retry window, which
is exactly the attribution an operator wants.

Hop latencies are computed between consecutive *present* stamps (a
skipped stage — e.g. ``enqueued`` for a group created Inqueue — skips
its hop), so per-hop sums always equal the e2e latency
(tests/test_lifecycle.py holds that identity).

All timestamps come from the caller (the store's clock), so a simulator
on a virtual clock produces bit-identical aggregates across double runs
(``fingerprint()``); the live scheduler stamps wall time. Aggregates
export as ``volcano_pod_e2e_latency_milliseconds{queue}`` /
``volcano_pod_hop_latency_milliseconds{hop}`` histograms and the
``/debug/latency`` endpoint serves p50/p95/p99 over a bounded sample
window. Enabled/disabled together with the tracer (one production
switch); a disabled ledger's ``stamp`` is one flag check.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from typing import Dict, List, Optional

STAGES = ("submitted", "enqueued", "session_eligible", "kernel_placed",
          "bind_staged", "store_committed", "echo_confirmed")
_STAGE_IDX = {s: i for i, s in enumerate(STAGES)}
DETOURS = ("retry", "quarantined", "healed")

# /debug/latency percentile window per hop (deterministic: the LAST N
# completions, not a randomized reservoir)
SAMPLE_WINDOW = 1024
# completed-bind ring for /debug/latency's recent view (key, trace, e2e)
RECENT_CAPACITY = 64

_enabled = False
_lock = threading.Lock()


class _Entry:
    __slots__ = ("stamps", "detours", "trace", "queue", "job")

    def __init__(self):
        self.stamps: List[tuple] = []       # [(stage_idx, t)] ascending
        self.detours: Optional[dict] = None
        self.trace: Optional[str] = None
        self.queue: Optional[str] = None
        self.job: Optional[str] = None

    def has(self, idx: int) -> bool:
        return any(i == idx for i, _ in self.stamps)


class _Agg:
    __slots__ = ("count", "total", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.samples: deque = deque(maxlen=SAMPLE_WINDOW)

    def add(self, ms: float) -> None:
        self.count += 1
        self.total += ms
        self.samples.append(ms)

    def percentiles(self) -> dict:
        if not self.samples:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        import math
        s = sorted(self.samples)
        n = len(s)
        # nearest-rank: index ceil(q*n) - 1 (int(q*n) alone reads one
        # rank high — p50 of two samples must be the first); the round
        # guards float fuzz like 0.95*20 == 19.000000000000004
        at = lambda q: s[min(n - 1, max(0, math.ceil(round(q * n, 9))
                                        - 1))]
        return {"p50": round(at(0.50), 3), "p95": round(at(0.95), 3),
                "p99": round(at(0.99), 3)}

    def report(self) -> dict:
        out = {"count": self.count,
               "mean_ms": round(self.total / self.count, 3)
               if self.count else 0.0}
        out.update(self.percentiles())
        return out


_entries: Dict[str, _Entry] = {}
_hops: Dict[str, _Agg] = {}          # "submitted->enqueued", ..., "e2e"
_queue_e2e: Dict[str, _Agg] = {}     # queue name -> e2e agg
_detour_totals: Dict[str, int] = {}
_recent: deque = deque(maxlen=RECENT_CAPACITY)
_completed = 0
_dropped = 0
# prometheus exports staged by completions under _lock, drained to
# metrics.observe_bulk AFTER release by the public entry points: one
# metrics-lock pass per (metric, label) per delivery instead of ~6 per
# completed pod (a 50k-bind flush echo otherwise pays ~300k lock
# acquisitions on the executor thread)
_pending_exports: Dict[tuple, list] = {}


# -- control ----------------------------------------------------------------


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    global _completed, _dropped
    with _lock:
        _entries.clear()
        _hops.clear()
        _queue_e2e.clear()
        _detour_totals.clear()
        _recent.clear()
        _pending_exports.clear()
        _completed = 0
        _dropped = 0


def _drain_exports() -> None:
    """Push staged histogram observations out (called by every public
    stamping entry point after releasing the ledger lock)."""
    if not _pending_exports:
        return
    with _lock:
        if not _pending_exports:
            return
        staged = dict(_pending_exports)
        _pending_exports.clear()
    from ..metrics import metrics as m
    for (name, labels), values in staged.items():
        m.observe_bulk(name, values, **dict(labels))


# -- stamping ---------------------------------------------------------------


def _stamp_locked(key: str, idx: int, now: float, queue, job, trace) -> None:
    e = _entries.get(key)
    if e is None:
        # ONLY the "submitted" stamp creates entries: a late stamp for a
        # pod whose entry already completed (the in-process store echoes
        # synchronously, so a store_committed stamp can arrive after the
        # echo confirmed and absorbed the entry) must never resurrect it
        # as a phantom open entry.
        if idx != 0:
            return
        e = _entries[key] = _Entry()
    if queue is not None:
        e.queue = queue
    if job is not None:
        e.job = job
    if trace is not None:
        e.trace = trace
    if e.has(idx):
        return
    # monotonic chain: a stage earlier than one already stamped is a
    # replay (restart relist, duplicate echo) — ignore it
    if e.stamps and idx < e.stamps[-1][0]:
        return
    if e.stamps and now < e.stamps[-1][1]:
        now = e.stamps[-1][1]   # clamp: hops are never negative
    e.stamps.append((idx, now))
    if idx == _STAGE_IDX["echo_confirmed"]:
        _complete_locked(key, e)


def stamp(key: str, stage: str, now: float, queue: Optional[str] = None,
          job: Optional[str] = None, trace: Optional[str] = None) -> None:
    """Record ``stage`` for pod ``key`` at time ``now`` (set-once)."""
    if not _enabled:
        return
    idx = _STAGE_IDX[stage]
    with _lock:
        _stamp_locked(key, idx, now, queue, job, trace)
    _drain_exports()


def stamp_bulk(keys, stage: str, now: float, trace: Optional[str] = None,
               queue: Optional[str] = None) -> None:
    """One lock pass for a batch point (the allocate batch, a flush's
    committed list, a shard's echo delivery)."""
    if not _enabled:
        return
    idx = _STAGE_IDX[stage]
    with _lock:
        for key in keys:
            _stamp_locked(key, idx, now, queue, None, trace)
    _drain_exports()


def confirm(key: str, now: float, queue: Optional[str] = None) -> None:
    """Bind-echo ingest: stamp ``store_committed`` then
    ``echo_confirmed`` in one lock pass. The in-process store delivers
    echoes synchronously from the committing write, so for it the two
    stamps coincide (a zero hop); a remote mirror's delayed echo leaves
    the earlier write-time store_committed stamp in place (set-once) and
    the hop measures the real propagation delay."""
    if not _enabled:
        return
    with _lock:
        _stamp_locked(key, _STAGE_IDX["store_committed"], now, queue,
                      None, None)
        _stamp_locked(key, _STAGE_IDX["echo_confirmed"], now, queue,
                      None, None)
    _drain_exports()


def confirm_bulk(items, now: float) -> None:
    """``confirm`` for a whole echo delivery: items = [(key, queue)]."""
    if not _enabled:
        return
    ci, ei = _STAGE_IDX["store_committed"], _STAGE_IDX["echo_confirmed"]
    with _lock:
        for key, queue in items:
            _stamp_locked(key, ci, now, queue, None, None)
            _stamp_locked(key, ei, now, queue, None, None)
    _drain_exports()


def detour(key: str, kind: str) -> None:
    """Count a retry/quarantined/healed detour on the pod's entry (a
    no-op for pods the ledger never saw submitted)."""
    if not _enabled:
        return
    with _lock:
        e = _entries.get(key)
        if e is None:
            return
        if e.detours is None:
            e.detours = {}
        e.detours[kind] = e.detours.get(kind, 0) + 1
        _detour_totals[kind] = _detour_totals.get(kind, 0) + 1


def reopen(key: str, kind: str, now: float) -> None:
    """A CONFIRMED bind was reverted (gang-atomic heal unbinding a bound
    sibling whose echo already completed its entry): count the detour
    unconditionally and restart the pod's lifecycle — a fresh entry
    re-submitted at the heal instant — so its eventual re-placement is
    tracked instead of every later stamp being dropped on the floor. An
    entry still OPEN (the remote-store shape, where the heal can run
    before the echo) just takes the detour; its original stamps stand
    and the staged->committed hop absorbs the heal window."""
    if not _enabled:
        return
    with _lock:
        _detour_totals[kind] = _detour_totals.get(kind, 0) + 1
        e = _entries.get(key)
        if e is None:
            e = _entries[key] = _Entry()
            e.stamps.append((0, now))
        if e.detours is None:
            e.detours = {}
        e.detours[kind] = e.detours.get(kind, 0) + 1


def drop(key: str) -> None:
    """The pod was deleted before confirmation: retire its entry so it
    can never show up as an orphan."""
    if not _enabled:
        return
    global _dropped
    with _lock:
        if _entries.pop(key, None) is not None:
            _dropped += 1


def _complete_locked(key: str, e: _Entry) -> None:
    global _completed
    del _entries[key]
    _completed += 1
    stamps = e.stamps
    e2e_ms = (stamps[-1][1] - stamps[0][1]) * 1000.0
    hop_ms: Dict[str, float] = {}
    for (i0, t0), (i1, t1) in zip(stamps, stamps[1:]):
        hop = f"{STAGES[i0]}->{STAGES[i1]}"
        hop_ms[hop] = (t1 - t0) * 1000.0
    for hop, ms in hop_ms.items():
        agg = _hops.get(hop)
        if agg is None:
            agg = _hops[hop] = _Agg()
        agg.add(ms)
    agg = _hops.get("e2e")
    if agg is None:
        agg = _hops["e2e"] = _Agg()
    agg.add(e2e_ms)
    q = e.queue or ""
    qagg = _queue_e2e.get(q)
    if qagg is None:
        qagg = _queue_e2e[q] = _Agg()
    qagg.add(e2e_ms)
    _recent.append({"pod": key, "trace": e.trace, "queue": q,
                    "e2e_ms": round(e2e_ms, 3),
                    "hops": {h: round(ms, 3) for h, ms in hop_ms.items()},
                    "detours": dict(e.detours) if e.detours else {}})
    # prometheus export rides the completion (staged here under _lock,
    # drained in bulk by the public entry point that triggered it)
    from ..metrics import metrics as m
    _pending_exports.setdefault(
        (m.POD_E2E_LATENCY, (("queue", q),)), []).append(e2e_ms)
    for hop, ms in hop_ms.items():
        _pending_exports.setdefault(
            (m.POD_HOP_LATENCY, (("hop", hop),)), []).append(ms)


# -- reading ----------------------------------------------------------------


def trace_of(key: str) -> Optional[str]:
    """The correlation ID recorded on a pod's OPEN ledger entry (completed
    binds surface theirs in ``report()['recent']``)."""
    with _lock:
        e = _entries.get(key)
        return e.trace if e is not None else None


def stats() -> dict:
    with _lock:
        return {"enabled": _enabled, "open": len(_entries),
                "completed": _completed, "dropped": _dropped,
                "detours": dict(_detour_totals)}


def orphans(store) -> List[str]:
    """Open entries whose pod no longer exists in the store — a stamp
    path that forgot to ``drop()`` on delete shows up here (the
    obs-smoke gate requires zero)."""
    with _lock:
        keys = list(_entries)
    out = []
    for key in keys:
        ns, _, name = key.partition("/")
        if store.get("pods", name, ns) is None:
            out.append(key)
    return out


def report() -> dict:
    """The ``/debug/latency`` payload: per-hop and e2e percentiles,
    per-queue e2e, detour totals, open/completed counts and the recent
    completion ring (pod -> trace id join)."""
    with _lock:
        return {
            "enabled": _enabled,
            "open": len(_entries),
            "completed": _completed,
            "dropped": _dropped,
            "detours": dict(_detour_totals),
            "hops": {hop: agg.report() for hop, agg in sorted(_hops.items())},
            "per_queue_e2e": {q: agg.report()
                              for q, agg in sorted(_queue_e2e.items())},
            "recent": list(_recent),
        }


def fingerprint() -> str:
    """Deterministic digest of the aggregate state — two virtual-clock
    sim runs from one seed must produce identical ledgers (the obs-smoke
    double-run gate)."""
    h = hashlib.sha256()
    with _lock:
        h.update(f"completed={_completed} dropped={_dropped}\n".encode())
        for kind in sorted(_detour_totals):
            h.update(f"detour {kind}={_detour_totals[kind]}\n".encode())
        for hop in sorted(_hops):
            agg = _hops[hop]
            h.update(f"hop {hop} n={agg.count} "
                     f"sum={agg.total:.9f}\n".encode())
        for q in sorted(_queue_e2e):
            agg = _queue_e2e[q]
            h.update(f"queue {q} n={agg.count} "
                     f"sum={agg.total:.9f}\n".encode())
    return h.hexdigest()
