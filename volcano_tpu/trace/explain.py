"""Placement explainer: decision provenance for the solver's kernels.

PR 1/6 made the scheduler's *time* observable; this module makes its
*decisions* observable — for every placed gang it answers "why did gang
G land on node N, what eliminated the other nodes, and what would a
top-k candidate shortlist lose?" (the ROADMAP's pruning item cannot be
built or validated without exactly that visibility; Tesserae — arxiv
2508.04953 — makes the same point for scalable policies, and the
priority-packing work — arxiv 2511.08373 — motivates the score-term
decomposition).

Three surfaces, all derived from the [G, N] mask/score tensors the
solver already compiles (framework/solver.py, ops/constraints.py) via
cheap reductions on-device — never a second placement pass:

* **Decision provenance** — per placed gang: the winning node, the
  per-constraint-mask elimination ladder (fit / selector / taint /
  affinity / spread / podcap / ...; counts telescope so ``feasible +
  sum(eliminations) == nodes`` exactly), the top-k surviving candidates
  with a score-term decomposition (binpack / least / most / balanced /
  static, plus the constraint compiler's tieredpack and soft-spread
  terms and the queue's proportion share), and the win margin (top-1 vs
  top-2 static score). Preempt/reclaim record the victim kernel's tier
  dispatch and per-victim admissibility verdicts (ops/victims.py).
  Scores are the SESSION-OPEN static formulation (the kernel's in-scan
  idle updates are not replayed) — the mask ladder and the winning node
  are exact, the candidate ordering is the pre-scan view the pruning
  work will shortlist from, which is precisely what it must measure.

* **Pruning-readiness aggregates** — per-gang feasible-node counts and
  top-k score-mass coverage (``volcano_gang_feasible_nodes``,
  ``volcano_topk_score_coverage{k}``): coverage is the fraction of a
  gang's total feasible score mass (min-shifted so it is >= 0) held by
  its k best candidates — 1.0 means a k-wide shortlist loses nothing.
  Exported into the bench row so the pruning PR has a baseline.

* **Fleet fragmentation** — ``volcano_fragmentation_ratio``: the
  largest schedulable uniform-gang (whole task-unit slots summed over
  nodes) vs the total free capacity in the same units; 1.0 = every free
  byte is reachable by a uniform gang, lower = per-node fragments below
  one task unit strand capacity (the Tesserae defrag pre-metric).

Gating: everything rides ``explain.enable`` (solver conf:
``explain.enable: "true"|"false"``, or :func:`enable` for tests/sim/
bench). When off, the only hot-path residue is one attribute check per
place() — the explain-smoke gate measures the off-mode overhead at <2%
alongside the tracer's own gate. Records are bounded (``RECORD_CAP``
jobs, LRU; ``VICTIM_CAP`` victim decisions) and the per-record score
decomposition caps at ``DETAIL_CAP`` per cycle so a 50k-gang bench
cycle pays aggregates-only cost for the tail.

Determinism: records carry no wall-clock state (cycle sequence comes
from the flight recorder), floats are rounded to 6 decimals, and
:func:`fingerprint` digests records in insertion order — bit-identical
across same-seed double runs (the `make explain-smoke` contract), and
folded into sim repro bundles (sim/replay.py).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

RECORD_CAP = 8192          # job records kept (LRU)
VICTIM_CAP = 1024          # victim-decision records kept (ring)
DETAIL_CAP = 1024          # per-cycle records that get the full top-k
#                            score-term decomposition (the rest keep the
#                            aggregate fields only)
TOPK = 8                   # candidates kept per record
COVERAGE_KS = (4, 16, 64)  # shortlist widths the coverage histograms
#                            measure (the pruning baseline axis)
_SAMPLE_CAP = 65536        # bounded aggregate sample window

PRUNE_RECENT_CAP = 256     # per-place prune/shortlist-loss records kept

_enabled = False
_lock = threading.Lock()
_records: "OrderedDict[str, dict]" = OrderedDict()   # job key -> record
_victims: deque = deque(maxlen=VICTIM_CAP)
_fp = hashlib.sha256()
_feas_samples: deque = deque(maxlen=_SAMPLE_CAP)
_cov_sum: Dict[int, float] = {}
_cov_count: Dict[int, int] = {}
_frag_ratio: Optional[float] = None
_detail_budget = DETAIL_CAP
_topk_fn_cache: Dict[tuple, object] = {}
# the operator-chosen shortlist width (solver conf `prune.k`) must
# always be one of the recorded coverage widths — a prune.k outside the
# static COVERAGE_KS would otherwise be flying blind on its loss budget
_extra_cov_ks: set = set()
# per-cycle shortlist-loss aggregates (ops/prune.py): recent per-place
# summaries + monotone totals, surfaced on /debug/explain
_prune_recent: deque = deque(maxlen=PRUNE_RECENT_CAP)
_prune_totals: Dict[str, Dict[str, int]] = {"runs": {}, "fallbacks": {}}


def _r(x) -> float:
    return round(float(x), 6)


# -- control ----------------------------------------------------------------


def enable() -> None:
    """Turn the explainer on process-wide (tests / sim / bench); the
    solver conf's ``explain.enable`` overrides per session."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop every record and aggregate (tests, double-run gates)."""
    global _fp, _frag_ratio, _detail_budget
    with _lock:
        _records.clear()
        _victims.clear()
        _fp = hashlib.sha256()
        _feas_samples.clear()
        _cov_sum.clear()
        _cov_count.clear()
        _frag_ratio = None
        _detail_budget = DETAIL_CAP
        _extra_cov_ks.clear()
        _prune_recent.clear()
        _prune_totals["runs"] = {}
        _prune_totals["fallbacks"] = {}


def register_prune_k(k: int) -> None:
    """Fold the solver conf's ``prune.k`` into the recorded coverage
    widths (sticky for the process; re-registered by every session that
    parses a prune-enabled conf, cleared by :func:`reset`)."""
    with _lock:
        _extra_cov_ks.add(int(k))


def coverage_ks() -> tuple:
    """The shortlist widths the coverage histograms measure: the static
    baseline axis plus any registered operator-chosen ``prune.k``."""
    with _lock:
        return tuple(sorted(set(COVERAGE_KS) | _extra_cov_ks))


def note_prune(rec: dict) -> None:
    """One place() call's shortlist-loss summary (ops/prune.py
    ``PruneContext.summary()``): pushed whether the reduced kernel
    served or a guard fell the cycle back — the per-cycle loss surface
    /debug/explain exposes. No wall-clock state, floats pre-rounded."""
    with _lock:
        _prune_recent.append(dict(rec))
        if rec.get("fallback"):
            key = str(rec["fallback"])
            _prune_totals["fallbacks"][key] = \
                _prune_totals["fallbacks"].get(key, 0) + 1
        else:
            key = str(rec.get("level", "single"))
            _prune_totals["runs"][key] = \
                _prune_totals["runs"].get(key, 0) + 1


def prune_report() -> dict:
    """The shortlist-loss aggregate block: totals + newest per-place
    summaries (the /debug/explain "prune" section)."""
    with _lock:
        recent = list(_prune_recent)
        totals = {"runs": dict(_prune_totals["runs"]),
                  "fallbacks": dict(_prune_totals["fallbacks"])}
    return {"totals": totals,
            "last": recent[-1] if recent else None,
            "recent": recent[-32:]}


def session_enabled(solver_args) -> bool:
    """The per-session switch the BatchSolver caches: the solver conf's
    ``explain.enable`` wins ("true"/"on" forces on, "false"/"off"
    forces off); unset defers to the module flag."""
    if solver_args is not None and hasattr(solver_args, "get_str"):
        v = (solver_args.get_str("explain.enable", "") or "").strip().lower()
        if v in ("true", "1", "yes", "on"):
            return True
        if v in ("false", "0", "no", "off"):
            return False
    return _enabled


# -- the fused aggregate kernel --------------------------------------------


def _topk_fn(k: int, ks: tuple):
    """One jitted pass over the final [G, N] mask + session-open score:
    feasible counts, top-k values/indices, min-shifted score-mass
    coverage per shortlist width, and the top-1 vs top-2 win margin.
    Cached per (k, ks); shapes re-jit per padded bucket like every
    other kernel. This is also the shortlist-distillation pass of the
    candidate-pruning regime (ops/prune.py) — mask -> shortlist is
    exactly this reduction, never a second predicate sweep. Widths are
    clamped to the node axis so a ``prune.k`` above the padded width
    (tiny fleets) degrades to full-width shortlists instead of a
    top_k shape error."""
    key = (k, ks)
    fn = _topk_fn_cache.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    from ..ops.score import node_score

    kmax = max(k, max(ks))

    @jax.jit
    def fused(group_req, idle, alloc, static, mask, weights):
        score = jax.vmap(
            lambda req, srow: node_score(req, idle, alloc, weights, srow)
        )(group_req, static)
        neg = jnp.float32(-1e30)
        n_ax = mask.shape[1]
        masked = jnp.where(mask, score, neg)
        vals, idx = jax.lax.top_k(masked, min(kmax, n_ax))
        feasible = mask.sum(axis=1)
        minf = jnp.min(jnp.where(mask, score, jnp.float32(1e30)), axis=1)
        total = jnp.where(mask, score - minf[:, None], 0.0).sum(axis=1)
        # the top-kk min-shifted score mass IS the top-kk masked values
        # shifted and clipped (identical values in identical order;
        # infeasible NEG entries clip to the 0 a masked-out column
        # contributes) — ONE top_k instead of two, which is the
        # difference between a fused pass and XLA re-materializing the
        # whole score chain per consumer (~10x at 50k x 10k)
        svals = jnp.maximum(vals - minf[:, None], 0.0)
        covs = [jnp.where(total > 0.0,
                          svals[:, :min(kk, n_ax)].sum(axis=1) / total, 1.0)
                for kk in ks]
        # NO in-jit win margin: a `vals[:, 0] - vals[:, 1]` consumer of
        # the top_k output defeats the XLA:CPU fusion of the whole pass
        # (measured 10x — 240 ms -> 2.4 s per 1024 x 10240 block);
        # callers derive it host-side from the returned values
        return feasible, vals[:, :k], idx[:, :k], jnp.stack(covs, axis=1)

    _topk_fn_cache[key] = fused
    return fused


# -- fleet fragmentation ----------------------------------------------------


def fragmentation_ratio(narr) -> float:
    """Largest schedulable uniform-gang vs total free capacity, from the
    (persistent) NodeArrays.

    The task unit is the fleet's median per-slot capability
    (allocatable / max_tasks over pod-capped ready nodes; the whole
    allocatable row when nothing is capped). Each node contributes
    ``min_r(idle_r / unit_r)`` fractional task slots; the largest
    uniform gang the fleet can schedule is the sum of the WHOLE slots,
    and the ratio is whole/fractional — 1.0 = unfragmented, lower =
    sub-unit fragments strand free capacity."""
    n = len(narr.names)
    if n == 0:
        return 1.0
    idle = narr.idle[:n]
    alloc = narr.allocatable[:n]
    max_t = narr.max_tasks[:n].astype(np.float64)
    capped = max_t > 0
    if capped.any():
        per_slot = alloc[capped] / np.maximum(max_t[capped, None], 1.0)
    else:
        per_slot = alloc
    unit = np.median(per_slot, axis=0)
    unit = np.where(unit > 0, unit, 1.0)
    frac = np.min(np.maximum(idle, 0.0) / unit[None, :], axis=1)
    whole = np.floor(frac)
    tot = float(frac.sum())
    if tot <= 0.0:
        return 1.0
    return float(whole.sum()) / tot


def note_fragmentation(narr) -> float:
    """Compute + publish the gauge; returns the ratio."""
    global _frag_ratio
    from ..metrics import metrics as m
    ratio = fragmentation_ratio(narr)
    _frag_ratio = ratio
    m.set_gauge(m.FRAGMENTATION_RATIO, round(ratio, 6))
    return ratio


# -- provenance capture (called from framework/solver._place) ---------------


def record_place(ssn, batch, narr, stages, gmask, static_score, weights,
                 assign, result, tier: str) -> None:
    """Build provenance records for every placed gang of one place()
    call. ``stages`` is the cumulative mask ladder the context build
    captured, already reduced to per-group survivor counts:
    ``[(label, survivors [G]), ...]`` (device or numpy); ``gmask`` is
    the final [G, n_pad] mask itself (padding columns False)."""
    import jax.numpy as jnp

    from ..metrics import metrics as m
    from ..trace import tracer

    global _detail_budget
    _detail_budget = DETAIL_CAP   # the detail cap is per place() batch
    if not stages:
        return
    n_real = len(narr.names)
    n_groups = int(batch.n_groups)
    if n_real == 0 or n_groups == 0:
        return

    # -- the elimination ladder: the captured per-stage survivor counts
    # plus the two final stages the kernels apply beyond the group mask
    pods_ok = (narr.max_tasks == 0) | (narr.n_tasks < narr.max_tasks)
    final = jnp.asarray(gmask) & jnp.asarray(pods_ok)[None, :]
    ladder: List[Tuple[str, object]] = list(stages) \
        + [("podcap", final.sum(axis=1))]
    if batch.task_slot is not None and batch.slot_rows is not None:
        # tensor-mode spread: the gang's per-task domain rows ride the
        # kernel's task_slot input, not the group mask — the record uses
        # the gang's FIRST task's row (domain-rotating gangs are
        # summarized by their first slot; the ladder still telescopes)
        group_slot = np.full(batch.g_pad, batch.slot_rows.shape[0] - 1,
                             np.int32)
        group_slot[:n_groups] = batch.task_slot[batch.group_first]
        final = final & jnp.asarray(batch.slot_rows)[
            jnp.asarray(group_slot)]
        ladder.append(("spread", final.sum(axis=1)))
    counts = [np.asarray(c).astype(np.int64) for _, c in ladder]

    # -- the fused aggregate pass (top-k, coverage, margin) -------------
    cov_ks = coverage_ks()
    fused = _topk_fn(TOPK, cov_ks)
    feasible_d, top_vals_d, top_idx_d, cov_d = fused(
        jnp.asarray(batch.group_req), jnp.asarray(narr.idle),
        jnp.asarray(narr.allocatable), jnp.asarray(static_score),
        final, weights)
    feasible = np.asarray(feasible_d).astype(np.int64)
    top_vals = np.asarray(top_vals_d)
    top_idx = np.asarray(top_idx_d)
    coverage = np.asarray(cov_d)
    # the top-1 vs top-2 win margin, host-side (see _topk_fn: an in-jit
    # cross-column consumer of the top_k output defeats the fusion)
    margin = np.where(feasible > 1, top_vals[:, 0] - top_vals[:, 1], 0.0)

    real = np.arange(n_groups)
    m.observe_bulk(m.GANG_FEASIBLE_NODES, feasible[real].tolist())
    for i, kk in enumerate(cov_ks):
        vals = coverage[real, i].tolist()
        m.observe_bulk(m.TOPK_SCORE_COVERAGE, vals, k=str(kk))
        with _lock:
            _cov_sum[kk] = _cov_sum.get(kk, 0.0) + float(sum(vals))
            _cov_count[kk] = _cov_count.get(kk, 0) + len(vals)
    with _lock:
        _feas_samples.extend(feasible[real].tolist())

    # -- per-gang records for the placed jobs ---------------------------
    n_tasks = len(batch.tasks)
    a_real = np.asarray(assign[:n_tasks])
    task_group = batch.task_group[:n_tasks]
    host_w = weights.host()
    cycle_seq = tracer.current_seq()
    elim_labels = [lab for lab, _ in ladder]
    names = narr.names
    share_by_queue = _queue_shares(ssn, batch)

    new_records: List[Tuple[str, dict]] = []
    for j, uid in enumerate(batch.job_uids):
        placements = result.placements.get(uid) or []
        if not placements:
            continue
        job = ssn.jobs.get(uid)
        jkey = f"{job.namespace}/{job.name}" if job is not None else uid
        lo, hi = int(batch.job_task_start[j]), int(batch.job_task_end[j])
        span = np.arange(lo, min(hi, n_tasks))
        placed_mask = a_real[span] >= 0
        groups_placed = sorted(
            set(task_group[span[placed_mask]].tolist()))
        qname = batch.queue_names[int(batch.job_queue[j])] \
            if int(batch.job_queue[j]) < len(batch.queue_names) else ""
        rec_groups = []
        for g in groups_placed:
            in_g = span[task_group[span] == g]
            placed_g = in_g[a_real[in_g] >= 0]
            winner = names[int(a_real[placed_g[0]])] \
                if len(placed_g) else None
            elims = {}
            prev = n_real
            for li, lab in enumerate(elim_labels):
                cur = int(counts[li][g])
                gone = prev - cur
                if gone > 0:
                    elims[lab] = elims.get(lab, 0) + gone
                prev = cur
            grec = {
                "gang": int(g),
                "tasks": int(len(in_g)),
                "placed": int(len(placed_g)),
                "winner": winner,
                "nodes": n_real,
                "feasible": int(feasible[g]),
                "eliminations": elims,
                "win_margin": _r(margin[g]),
                "coverage": {str(kk): _r(coverage[g, i])
                             for i, kk in enumerate(cov_ks)},
            }
            if _detail_budget > 0:
                _detail_budget -= 1
                grec["topk"] = _topk_detail(
                    ssn, batch, narr, host_w, static_score, g,
                    top_vals[g], top_idx[g])
            rec_groups.append(grec)
        if not rec_groups:
            continue
        rec = {
            "job": jkey, "uid": uid, "cycle": cycle_seq, "kernel": tier,
            "queue": qname,
            "proportion_share": share_by_queue.get(qname),
            "committed": bool(result.committed.get(uid)),
            "pipelined_only": bool(result.kept.get(uid)
                                   and not result.committed.get(uid)),
            "groups": rec_groups,
        }
        new_records.append((jkey, rec))

    if not new_records:
        return
    with _lock:
        for jkey, rec in new_records:
            _records.pop(jkey, None)
            _records[jkey] = rec
            while len(_records) > RECORD_CAP:
                _records.popitem(last=False)
            _fp.update(_fp_line(rec).encode())


def _queue_shares(ssn, batch) -> Dict[str, Optional[float]]:
    """The proportion context per queue: max over resources of
    allocated/deserved from the live queue budgets (None when no budget
    fn is registered or the queue has no finite deserved row)."""
    shares: Dict[str, Optional[float]] = {}
    solver = getattr(ssn, "solver", None)
    fns = getattr(solver, "queue_budget_fns", None) or []
    for qname in batch.queue_names:
        share = None
        for fn in fns:
            budget = fn(qname, solver.rindex)
            if budget is None:
                continue
            allocated, deserved = budget
            finite = np.isfinite(deserved) & (deserved > 0)
            if finite.any():
                share = _r(np.max(allocated[finite] / deserved[finite]))
            break
        shares[qname] = share
    return shares


def _topk_detail(ssn, batch, narr, host_w, static_score, g,
                 vals, idx) -> List[dict]:
    """Score-term decomposition for one gang's top-k candidates:
    the kernel's additive terms recomputed host-side for just those
    nodes, plus the constraint compiler's per-term values."""
    from ..ops import constraints
    from ..ops.score import (balanced_allocation_score, binpack_score,
                             least_requested_score, most_requested_score)
    n_real = len(narr.names)
    keep = [i for i in range(len(idx))
            if vals[i] > -1e29 and 0 <= int(idx[i]) < n_real]
    if not keep:
        return []
    nodes = np.asarray([int(idx[i]) for i in keep])
    req = batch.group_req[g]
    idle = narr.idle[nodes]
    alloc = narr.allocatable[nodes]
    used = alloc - idle
    terms = {}
    if float(host_w.binpack):
        terms["binpack"] = float(host_w.binpack) * binpack_score(
            req, used, alloc, host_w.binpack_res, np)
    if float(host_w.least):
        terms["least"] = float(host_w.least) * least_requested_score(
            req, used, alloc, np)
    if float(host_w.most):
        terms["most"] = float(host_w.most) * most_requested_score(
            req, used, alloc, np)
    if float(host_w.balanced):
        terms["balanced"] = float(host_w.balanced) * \
            balanced_allocation_score(req, used, alloc, np)
    import jax.numpy as jnp
    static_vals = np.asarray(
        jnp.asarray(static_score)[g, jnp.asarray(nodes)])
    rep = batch.tasks[int(batch.group_first[g])]
    cterms = constraints.score_terms_for(
        ssn, rep, [narr.names[i] for i in nodes],
        tiered_weight=getattr(ssn, "_tieredpack_weight", 0.0))
    out = []
    for pos, i in enumerate(keep):
        entry = {"node": narr.names[int(idx[i])],
                 "score": _r(vals[i]),
                 "terms": {name: _r(col[pos])
                           for name, col in terms.items()}}
        entry["terms"]["static"] = _r(static_vals[pos])
        for name, col in cterms.items():
            entry["terms"][name] = _r(col[pos])
        out.append(entry)
    return out


# -- victim provenance (called from ops/victims.py) -------------------------


def record_victims(preemptor_key: str, mode: str, node: str,
                   tiers, admissible: Dict[str, int], candidates: int,
                   winning_tier: Optional[int], victims: List[str],
                   verdicts: List[dict], covered: bool) -> None:
    """One preempt/reclaim decision: which tier dispatched, how many
    candidates each plugin admitted, and the per-victim verdicts on the
    winning node."""
    rec = {
        "preemptor": preemptor_key, "mode": mode, "node": node,
        "tiers": [[int(ti), list(names)] for ti, names in tiers],
        "winning_tier": winning_tier,
        "candidates": int(candidates),
        "admissible": {k: int(v) for k, v in admissible.items()},
        "victims": list(victims),
        "covered": bool(covered),
        "verdicts": verdicts,
    }
    with _lock:
        _victims.append(rec)
        _fp.update(_fp_victim_line(rec).encode())


# -- reading ----------------------------------------------------------------


def _fp_line(rec: dict) -> str:
    # the cycle seq is display metadata: it rides the flight recorder's
    # GLOBAL sequence, which keeps counting across same-process runs —
    # hashing it would break the double-run identity the smoke asserts
    parts = [rec["job"], rec["kernel"]]
    for g in rec["groups"]:
        elims = ",".join(f"{k}={v}" for k, v in sorted(
            g["eliminations"].items()))
        topk = ";".join(e["node"] for e in g.get("topk", []))
        parts.append(f"g{g['gang']}:{g['winner']}:{g['feasible']}"
                     f":{elims}:{g['win_margin']}:{topk}")
    return "|".join(parts) + "\n"


def _fp_victim_line(rec: dict) -> str:
    return (f"victim|{rec['preemptor']}|{rec['mode']}|{rec['node']}|"
            f"{rec['winning_tier']}|{','.join(rec['victims'])}\n")


def fingerprint() -> str:
    """Deterministic digest of every record in insertion order — the
    double-run identity the explain-smoke gate asserts."""
    with _lock:
        return _fp.hexdigest()


def job_record(key: str) -> Optional[dict]:
    """The latest record for a job ("ns/name" key or uid)."""
    with _lock:
        rec = _records.get(key)
        if rec is not None:
            return dict(rec)
        for r in _records.values():
            if r.get("uid") == key:
                return dict(r)
    return None


def _percentiles(samples: List[int]) -> dict:
    if not samples:
        return {"count": 0}
    import math
    s = sorted(samples)
    n = len(s)
    # nearest-rank: index ceil(q*n) - 1 (trace/ledger.py's form — int(q*n)
    # alone reads one rank high: p50 of two samples must be the first)
    at = lambda q: s[min(n - 1, max(0, math.ceil(round(q * n, 9)) - 1))]
    return {"count": n, "mean": _r(sum(s) / n),
            "min": int(s[0]), "p50": int(at(0.5)), "p90": int(at(0.9)),
            "p99": int(at(0.99)), "max": int(s[-1])}


def aggregates() -> dict:
    """The pruning-readiness surface: feasible-node percentiles, mean
    top-k score-mass coverage per shortlist width, fragmentation."""
    with _lock:
        feas = list(_feas_samples)
        cov = {str(k): _r(_cov_sum[k] / _cov_count[k])
               for k in sorted(_cov_sum) if _cov_count.get(k)}
        frag = _frag_ratio
    return {"feasible_nodes": _percentiles(feas),
            "topk_coverage": cov,
            "coverage_ks": list(coverage_ks()),
            "fragmentation_ratio": _r(frag) if frag is not None else None,
            "prune": prune_report()}


def report(limit: int = 64) -> dict:
    """The /debug/explain payload: records (newest ``limit``; 0 = all),
    victim decisions, aggregates, fingerprint."""
    with _lock:
        jobs = list(_records.items())
        victims = list(_victims)
        n_records = len(_records)
        fp = _fp.hexdigest()
    if limit and len(jobs) > limit:
        jobs = jobs[-limit:]
    return {
        "enabled": _enabled,
        "records": n_records,
        "fingerprint": fp,
        "jobs": {k: v for k, v in jobs},
        "victims": victims[-limit:] if limit else victims,
        "aggregates": aggregates(),
    }
