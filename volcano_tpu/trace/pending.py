""""Why pending" diagnosis: aggregate the cycle's unschedulable reasons.

The scheduler already *collects* per-task failure detail — FitErrors from
the solver's mask summaries (framework/solver.py _record_fit_errors) and
the host predicate path (plugins/predicates.py FitException reasons), plus
the gang plugin's Unschedulable PodGroup conditions — but nothing
aggregated it into an answerable "why is this task still pending".
``collect(ssn)`` rolls those sources into per-job and per-reason counts;
``publish(ssn)`` (called at session close while tracing is on) stores the
report for the ``/debug/pending`` endpoint and bumps the
``volcano_unschedulable_reason_total`` counters.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict

from ..models.job_info import TaskStatus
from ..models.objects import PodGroupConditionType, PodGroupPhase
from . import tracer

# canonical reasons for the solver's summarized (mask-level) fit errors,
# matched against FitErrors.err strings set by _record_fit_errors
REASON_SOLVER_MASKED = "predicates failed or insufficient resources"
REASON_GANG_ROLLBACK = "gang rollback or all feasible nodes already full"
REASON_NOT_CONSIDERED = "not considered this cycle"
REASON_AWAITING_ENQUEUE = "PodGroup awaiting enqueue (Pending phase)"
# commit-path resilience (docs/design/resilience.md): pods the cache has
# made ineligible for re-placement — quarantined after exhausting their
# bind retry budget, or inside a bind-failure backoff window (the latter
# is suffixed "(attempt N)", bounded by the retry budget)
REASON_QUARANTINED = "bind quarantined: retry budget exhausted"
REASON_BIND_BACKOFF = "bind failed: in retry backoff"
# control-plane failover (docs/design/failover.md): windows where the
# scheduler is deliberately NOT scheduling — a standby waiting out the
# leader lease, or the cache mid-relist after an anti-entropy divergence
# — surface as explicit reasons instead of a silently stale report
REASON_NOT_LEADER = "scheduler not leader (standby)"
REASON_CACHE_RESYNC = "cache resync in progress"


def _task_reasons(fe) -> Counter:
    """Distinct reasons of one task's FitErrors: per-node predicate
    reasons when present, else the classified summary error."""
    reasons: Counter = Counter()
    if fe.nodes:
        seen = set()
        for node_fe in fe.nodes.values():
            seen.update(node_fe.reasons)
        for r in seen:
            reasons[r] += 1
        if seen:
            return reasons
    err = fe.err or ""
    if REASON_SOLVER_MASKED in err:
        reasons[REASON_SOLVER_MASKED] += 1
    elif "gang rollback" in err:
        reasons[REASON_GANG_ROLLBACK] += 1
    elif err:
        reasons[err] += 1
    return reasons


def collect(ssn) -> dict:
    """Per-job and per-reason pending counts for one session. A reason
    counts once per task (a task blocked on 9k nodes by the same
    predicate is one pending task, not 9k)."""
    jobs: Dict[str, dict] = {}
    totals: Counter = Counter()
    ineligible = getattr(ssn, "ineligible_binds", None) or {}
    for job in ssn.jobs.values():
        if job.pod_group is None or job.ready():
            continue
        pending = len(job.task_status_index.get(TaskStatus.Pending, {}))
        unready = max(0, job.min_available - job.ready_task_num())
        if not pending and not unready:
            continue
        per_reason: Counter = Counter()
        for fe in job.nodes_fit_errors.values():
            per_reason.update(_task_reasons(fe))
        had_fit_errors = bool(per_reason)
        gated = 0
        if ineligible:
            # quarantined / backoff-gated pods were skipped by the
            # placing actions, so they carry no fit errors — surface the
            # cache's ineligibility reason instead
            for task in job.task_status_index.get(
                    TaskStatus.Pending, {}).values():
                reason = ineligible.get(task.key())
                if reason:
                    per_reason[reason] += 1
                    gated += 1
        cond_reason = ""
        cond_message = ""
        for c in job.pod_group.status.conditions:
            if c.type == PodGroupConditionType.UNSCHEDULABLE \
                    and c.status == "True":
                cond_reason, cond_message = c.reason, c.message
        if not had_fit_errors:
            # no fit errors recorded: the job never reached the solver
            # this cycle (still Pending-phase, dropped by JobValid,
            # starved by ordering, or its eligible tasks parked behind a
            # gated gang mate). Count by max(pending, unready): a
            # Pending-phase group's pods don't exist yet, so its
            # Pending-status task count is 0 while min_available-unready
            # is the real shortfall. Gated tasks already carry their own
            # reason above — count only the remainder here, so a gang
            # with one quarantined pod still reports its other stuck
            # tasks instead of vanishing from the backlog.
            rest = (max(pending, unready) or 1) - gated
            if rest > 0:
                if job.pod_group.status.phase == PodGroupPhase.PENDING:
                    per_reason[REASON_AWAITING_ENQUEUE] = rest
                else:
                    per_reason[cond_reason or REASON_NOT_CONSIDERED] = rest
        totals.update(per_reason)
        jobs[f"{job.namespace}/{job.name}"] = {
            "queue": job.queue,
            "pending_tasks": pending,
            "unready": unready,
            "min_available": job.min_available,
            "condition_reason": cond_reason,
            "message": cond_message or job.job_fit_errors,
            "reasons": dict(per_reason),
        }
    return {"pending_jobs": len(jobs), "reasons": dict(totals),
            "jobs": jobs}


def publish(ssn) -> dict:
    """Collect + store for /debug/pending + export the per-reason
    counters (``volcano_unschedulable_reason_total``)."""
    from ..metrics import metrics as m
    report = collect(ssn)
    report["cycle_seq"] = tracer.current_seq()
    report["session_uid"] = getattr(ssn, "uid", "")
    for reason, count in report["reasons"].items():
        m.inc(m.UNSCHEDULABLE_REASON, float(count), reason=reason)
    tracer.set_pending_report(report)
    return report


def publish_idle(reason: str, detail: str = "") -> dict:
    """Publish a whole-scheduler idle reason to ``/debug/pending`` — no
    session ran, so there are no per-job rows, but during a failover
    window ("scheduler not leader (standby)", "cache resync in
    progress") the endpoint must say WHY nothing is being scheduled
    rather than serving the last leader's stale report."""
    from ..metrics import metrics as m
    report = {"pending_jobs": 0, "reasons": {reason: 1}, "jobs": {},
              "idle_reason": reason, "detail": detail,
              "cycle_seq": tracer.current_seq()}
    m.inc(m.UNSCHEDULABLE_REASON, 1.0, reason=reason)
    tracer.set_pending_report(report)
    return report
