"""Cycle flight recorder (tracer) + "why pending" diagnosis (pending)."""

from . import tracer  # noqa: F401
