"""Cycle flight recorder (tracer), pod lifecycle ledger (ledger) and
"why pending" diagnosis (pending)."""

from . import ledger  # noqa: F401
from . import tracer  # noqa: F401
