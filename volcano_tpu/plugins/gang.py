"""gang plugin (reference: pkg/scheduler/plugins/gang/gang.go).

Extension points: JobValid (minAvailable admission), Preemptable/Reclaimable
(victims only above minAvailable), JobOrder (ready jobs last), JobReady,
JobPipelined, JobStarving; OnSessionClose writes Unschedulable/Scheduled
PodGroup conditions and unschedulable metrics.

The gang *commit/rollback* semantics themselves live in the allocate kernel
(ops/allocate.py) whose per-job ready/kept flags implement exactly this
plugin's JobReady/JobPipelined formulas.
"""

from __future__ import annotations

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT, REJECT, ValidateResult
from ..framework import framework as fw
from ..metrics import metrics as m
from ..models.job_info import TaskStatus
from ..models.objects import (NOT_ENOUGH_PODS_REASON,
                              NOT_ENOUGH_RESOURCES_REASON, PodGroupCondition,
                              PodGroupConditionType, POD_GROUP_READY)
from ..models.unschedule_info import FitErrors

NAME = "gang"


class GangPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        def valid_job_fn(job):
            """minAvailable admission (gang.go:50-79)."""
            if not job.check_task_min_available():
                return ValidateResult(
                    False, NOT_ENOUGH_PODS_REASON,
                    "Not enough valid pods of each task for gang-scheduling")
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    False, NOT_ENOUGH_PODS_REASON,
                    f"Not enough valid tasks for gang-scheduling, "
                    f"valid: {vtn}, min: {job.min_available}")
            return None

        ssn.add_job_valid_fn(NAME, valid_job_fn)

        def preemptable_fn(preemptor, preemptees):
            """Victims only while their job stays above minAvailable
            (gang.go:83-105)."""
            victims = []
            occupied = {}
            for preemptee in preemptees:
                job = ssn.jobs.get(preemptee.job)
                if job is None:
                    continue
                if job.uid not in occupied:
                    occupied[job.uid] = job.ready_task_num()
                if occupied[job.uid] > job.min_available:
                    occupied[job.uid] -= 1
                    victims.append(preemptee)
            return victims, PERMIT

        ssn.add_reclaimable_fn(NAME, preemptable_fn)
        ssn.add_preemptable_fn(NAME, preemptable_fn)

        def job_order_fn(l, r):
            """Unready jobs first (gang.go:111-134)."""
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(NAME, job_order_fn)
        ssn.add_job_ready_fn(NAME, lambda job: job.ready())

        def pipelined_fn(job):
            occupied = job.waiting_task_num() + job.ready_task_num()
            return PERMIT if occupied >= job.min_available else REJECT

        ssn.add_job_pipelined_fn(NAME, pipelined_fn)

        def job_starving_fn(job):
            occupied = job.waiting_task_num() + job.ready_task_num()
            return occupied < job.min_available

        ssn.add_job_starving_fns(NAME, job_starving_fn)

    def on_session_close(self, ssn) -> None:
        """Write gang conditions + unschedulable metrics (gang.go:160-219)."""
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if job.pod_group is None:
                continue
            if not job.ready():
                # deferred placements of kept (pipelined) gangs must be
                # real before the unready report reads task statuses
                ssn.materialize_job(job)
                unready = job.min_available - job.ready_task_num()
                msg = (f"{unready}/{len(job.tasks)} tasks in gang "
                       f"unschedulable: {job.fit_error()}")
                job.job_fit_errors = msg
                unschedulable_jobs += 1
                fw.update_pod_group_condition(ssn, job, PodGroupCondition(
                    type=PodGroupConditionType.UNSCHEDULABLE, status="True",
                    transition_id=ssn.uid,
                    reason=NOT_ENOUGH_RESOURCES_REASON, message=msg))
                for task in job.task_status_index.get(TaskStatus.Allocated, {}).values():
                    if task.uid not in job.nodes_fit_errors:
                        fe = FitErrors()
                        fe.set_error(msg)
                        job.nodes_fit_errors[task.uid] = fe
                m.update_unschedulable_task_count(job.name, max(0, unready))
            else:
                # refreshing an identical Scheduled condition would only
                # bump transition_id (nothing reads it for Scheduled —
                # job_status consults it for Unschedulable only), but it
                # claims a COW PodGroup per ready job per cycle; skip when
                # an equivalent condition is already present
                if not any(c.type == PodGroupConditionType.SCHEDULED
                           and c.status == "True"
                           and c.reason == POD_GROUP_READY
                           for c in job.pod_group.status.conditions):
                    fw.update_pod_group_condition(ssn, job, PodGroupCondition(
                        type=PodGroupConditionType.SCHEDULED, status="True",
                        transition_id=ssn.uid, reason=POD_GROUP_READY))
                m.update_unschedulable_task_count(job.name, 0)
        m.set_gauge(m.UNSCHEDULE_JOB_COUNT, unschedulable_jobs)


register_plugin_builder(NAME, GangPlugin)
