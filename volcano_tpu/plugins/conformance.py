"""conformance plugin (reference: pkg/scheduler/plugins/conformance/
conformance.go).

Shields cluster-critical pods from preemption/reclamation: tasks in the
kube-system namespace or carrying the system-cluster-critical /
system-node-critical priority classes are filtered out of every victim set
(conformance.go:45-66).
"""

from __future__ import annotations

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT

NAME = "conformance"

SYSTEM_NAMESPACE = "kube-system"
SYSTEM_CLUSTER_CRITICAL = "system-cluster-critical"
SYSTEM_NODE_CRITICAL = "system-node-critical"


class ConformancePlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        def evictable_fn(evictor, evictees):
            victims = []
            for evictee in evictees:
                class_name = evictee.pod.spec.priority_class_name
                if (class_name in (SYSTEM_CLUSTER_CRITICAL,
                                   SYSTEM_NODE_CRITICAL)
                        or evictee.namespace == SYSTEM_NAMESPACE):
                    continue
                victims.append(evictee)
            return victims, PERMIT

        ssn.add_preemptable_fn(NAME, evictable_fn)
        ssn.add_reclaimable_fn(NAME, evictable_fn)

    def on_session_close(self, ssn) -> None:
        pass


register_plugin_builder(NAME, ConformancePlugin)
