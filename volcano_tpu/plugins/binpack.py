"""binpack plugin (reference: pkg/scheduler/plugins/binpack/binpack.go).

Best-fit node scoring: score_r = (used_r + request_r) / allocatable_r,
weighted per resource and normalized x100 (binpack.go:200-260). Arguments
(binpack.go:105-150):

    binpack.weight               -- overall plugin weight (default 1)
    binpack.cpu                  -- per-resource weights (default 1)
    binpack.memory
    binpack.resources            -- "nvidia.com/gpu,example.com/foo"
    binpack.resources.<name>     -- weight for each extra resource

TPU-first: the scoring itself runs inside the allocate scan
(ops/score.py binpack_score) against the live idle state; this plugin just
feeds the weights into the session solver and registers the host-side
NodeOrderFn for single-pair paths.
"""

from __future__ import annotations

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..models.resource import CPU, MEMORY

NAME = "binpack"


class BinpackPlugin(Plugin):
    def __init__(self, arguments=None):
        args = arguments or {}
        self.weight = args.get_int("binpack.weight", 1) if hasattr(args, "get_int") \
            else int(args.get("binpack.weight", 1))
        get = args.get_int if hasattr(args, "get_int") else \
            (lambda k, d: int(args.get(k, d)))
        self.res_weights = {CPU: get("binpack.cpu", 1),
                            MEMORY: get("binpack.memory", 1)}
        resources = str(args.get("binpack.resources", "") or "")
        for res in resources.split(","):
            res = res.strip()
            if res:
                self.res_weights[res] = get(f"binpack.resources.{res}", 1)

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        if ssn.solver is not None and ssn.plugin_enabled(NAME, "enabledNodeOrder"):
            ssn.solver.add_weight("binpack", float(self.weight))
            ssn.solver.set_binpack_resources(
                {k: float(v) for k, v in self.res_weights.items()})
            ssn.solver.mark_vectorized(NAME)

        def node_order_fn(task, node) -> float:
            return self._score(task, node)

        ssn.add_node_order_fn(NAME, node_order_fn)

    def _score(self, task, node) -> float:
        """Host-side mirror of ops/score.py binpack_score."""
        score = 0.0
        weight_sum = 0.0
        for res, w in self.res_weights.items():
            request = task.resreq.get(res)
            if request <= 0 or w <= 0:
                continue
            alloc = node.allocatable.get(res)
            if alloc <= 0:
                continue
            used = node.used.get(res)
            # an overflowing resource contributes 0 but stays in the
            # normalization, matching ops/score.py binpack_score
            if used + request <= alloc:
                score += w * (used + request) * 100.0 / alloc
            weight_sum += w
        if weight_sum == 0:
            return 0.0
        return score / weight_sum * self.weight


register_plugin_builder(NAME, BinpackPlugin)
