"""drf plugin (reference: pkg/scheduler/plugins/drf/drf.go).

Dominant Resource Fairness: per-job share = max_r allocated_r / total_r.
Extension points: Preemptable (preemptor share must stay below preemptee's,
with optional namespace-weighted policy), JobOrder (lowest share first),
NamespaceOrder, and — with ``enabledHierarchy`` — hierarchical DRF:
QueueOrder over the weighted share tree and Reclaimable via what-if tree
updates. Event handlers keep shares live as the session allocates/evicts.

TPU-first: the initial per-job share computation is one ``dominant_share``
kernel call over a dense [J,R] allocation matrix (ops/fairshare.py) instead
of J×R host loops; incremental in-session updates are O(R) host math like
the reference's.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT, EventHandler
from ..metrics import metrics as m
from ..models.arrays import ResourceIndex
from ..models.resource import Resource

NAME = "drf"
SHARE_DELTA = 0.000001


def _share_of(allocated: Resource, total: Resource) -> (str, float):
    """(dominant resource, share) with 0/0=0, x/0=1 (drf.go:621-646)."""
    res, dom = 0.0, ""
    for rn in total.resource_names():
        t = total.get(rn)
        a = allocated.get(rn)
        s = ((0.0 if a == 0 else 1.0) if t == 0 else a / t)
        if s > res:
            res, dom = s, rn
    return dom, res


class _DrfAttr:
    __slots__ = ("share", "dominant", "allocated", "version")

    def __init__(self, allocated: Optional[Resource] = None):
        self.share = 0.0
        self.dominant = ""
        self.allocated = allocated if allocated is not None else Resource()
        # bumped on every allocated mutation: preemptable_fn memoizes the
        # preemptor-side share against it (5k preemptors x ~3 node visits
        # re-derived the same clone+add+share chain otherwise)
        self.version = 0


class _HNode:
    """Hierarchical-DRF tree node (drf.go:42-76)."""

    __slots__ = ("parent", "attr", "request", "weight", "saturated",
                 "hierarchy", "children")

    def __init__(self, hierarchy: str, weight: float = 1.0,
                 attr: Optional[_DrfAttr] = None, leaf: bool = False):
        self.parent: Optional[_HNode] = None
        self.attr = attr if attr is not None else _DrfAttr()
        self.request = Resource()
        self.weight = weight
        self.saturated = False
        self.hierarchy = hierarchy
        self.children: Optional[Dict[str, _HNode]] = None if leaf else {}

    def clone(self, parent: Optional["_HNode"]) -> "_HNode":
        n = _HNode(self.hierarchy, self.weight,
                   leaf=self.children is None)
        n.parent = parent
        n.attr = _DrfAttr(self.attr.allocated.clone())
        n.attr.share = self.attr.share
        n.attr.dominant = self.attr.dominant
        n.request = self.request.clone()
        n.saturated = self.saturated
        if self.children is not None:
            n.children = {k: c.clone(n) for k, c in self.children.items()}
        return n


def _resource_saturated(allocated: Resource, request: Resource,
                        demanding: Dict[str, bool]) -> bool:
    """A leaf is saturated once any requested resource is fully allocated or
    a requested resource has no cluster headroom left (drf.go:78-93)."""
    for rn in allocated.resource_names():
        a, r = allocated.get(rn), request.get(rn)
        if a != 0 and r != 0 and a >= r:
            return True
        if not demanding.get(rn, False) and r != 0:
            return True
    return False


class DrfPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.total = Resource()
        self.total_allocated = Resource()
        self.job_attrs: Dict[str, _DrfAttr] = {}
        self.namespace_opts: Dict[str, _DrfAttr] = {}
        self.root = _HNode("root", 1.0)
        self._touched_jobs: set = set()

    def name(self) -> str:
        return NAME

    # -- session open ------------------------------------------------------

    def on_session_open(self, ssn) -> None:
        self.total = ssn.total_resource.clone()
        ns_enabled = ssn.plugin_enabled(NAME, "enabledNamespaceOrder") and \
            any(opt.name == NAME and "enabledNamespaceOrder" in opt.enabled
                for tier in ssn.tiers for opt in tier.plugins)
        hier_enabled = any(
            opt.name == NAME and opt.enabled.get("enabledHierarchy", False)
            for tier in ssn.tiers for opt in tier.plugins)

        # initial shares: one dense kernel call over [J, R]
        jobs = list(ssn.jobs.values())
        for job in jobs:
            # JobInfo.allocated is maintained as exactly the sum of
            # allocated-status task requests (add/delete/move paths), so
            # the per-task resum is one clone (drf.go:202-230 sums tasks
            # because Go's JobInfo lacks the running aggregate)
            attr = _DrfAttr(job.allocated.clone())
            self.job_attrs[job.uid] = attr
        self._batch_update_shares(jobs)
        for job in jobs:
            attr = self.job_attrs[job.uid]
            m.update_job_share(job.namespace, job.name, attr.share)
            if ns_enabled:
                ns = self.namespace_opts.setdefault(job.namespace, _DrfAttr())
                ns.allocated.add(attr.allocated)
            if hier_enabled:
                queue = ssn.queues.get(job.queue)
                if queue is not None:
                    self.total_allocated.add(attr.allocated)
                    self._update_hierarchical_share(
                        self.root, self.total_allocated, job, attr,
                        queue.hierarchy, queue.hierarchical_weights)
        if ns_enabled:
            for ns, opt in self.namespace_opts.items():
                opt.dominant, opt.share = _share_of(opt.allocated, self.total)
                m.update_namespace_share(ns, opt.share)
            if ssn.solver is not None:
                def ns_budget(ns_name, rindex):
                    """Session-open namespace allocation + weight for the
                    kernel's live namespace re-selection (the in-scan form
                    of namespace_order_fn below; drf.go ns ordering)."""
                    opt = self.namespace_opts.get(ns_name)
                    info = ssn.namespace_info.get(ns_name)
                    weight = info.get_weight() if info else 1
                    alloc = rindex.vec(opt.allocated) if opt is not None \
                        else np.zeros(rindex.r, np.float32)
                    return alloc, float(weight)
                ssn.solver.set_namespace_budget_fn(ns_budget)

        _ls_memo: Dict[tuple, float] = {}

        def preemptable_fn(preemptor, preemptees):
            """Preemption allowed only while it narrows the share gap
            (drf.go:246-330)."""
            victims = []
            if ns_enabled:
                ns_info = ssn.namespace_info.get(preemptor.namespace)
                l_weight = ns_info.get_weight() if ns_info else 1
                l_ns = self.namespace_opts.get(preemptor.namespace, _DrfAttr())
                l_ns_alloc = l_ns.allocated.clone().add(preemptor.resreq)
                _, l_ns_share = _share_of(l_ns_alloc, self.total)
                l_ns_weighted = l_ns_share / l_weight

                ns_allocs: Dict[str, Resource] = {}
                undecided = []
                for preemptee in preemptees:
                    if preemptor.namespace == preemptee.namespace:
                        undecided.append(preemptee)
                        continue
                    alloc = ns_allocs.get(preemptee.namespace)
                    if alloc is None:
                        r_ns = self.namespace_opts.get(preemptee.namespace,
                                                       _DrfAttr())
                        alloc = r_ns.allocated.clone()
                        ns_allocs[preemptee.namespace] = alloc
                    r_info = ssn.namespace_info.get(preemptee.namespace)
                    r_weight = r_info.get_weight() if r_info else 1
                    alloc.sub(preemptee.resreq)
                    _, r_ns_share = _share_of(alloc, self.total)
                    r_ns_weighted = r_ns_share / r_weight
                    if l_ns_weighted < r_ns_weighted:
                        victims.append(preemptee)
                        continue
                    if l_ns_weighted - r_ns_weighted > SHARE_DELTA:
                        continue
                    undecided.append(preemptee)
                preemptees = undecided

            latt = self.job_attrs.get(preemptor.job, _DrfAttr())
            lkey = (preemptor.job, latt.version, id(preemptor.resreq))
            ls = _ls_memo.get(lkey)
            if ls is None:
                lalloc = latt.allocated.clone().add(preemptor.resreq)
                _, ls = _share_of(lalloc, self.total)
                _ls_memo[lkey] = ls

            allocations: Dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    ratt = self.job_attrs.get(preemptee.job, _DrfAttr())
                    allocations[preemptee.job] = ratt.allocated.clone()
                ralloc = allocations[preemptee.job].sub(preemptee.resreq)
                _, rs = _share_of(ralloc, self.total)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims, PERMIT

        ssn.add_preemptable_fn(NAME, preemptable_fn)

        if hier_enabled:
            def queue_order_fn(l, r) -> int:
                v = self._compare_queues(self.root, l, r)
                return 0 if v == 0 else (-1 if v < 0 else 1)

            ssn.add_queue_order_fn(NAME, queue_order_fn)

            def reclaimable_fn(reclaimer, reclaimees):
                """What-if tree evaluation per reclaimee (drf.go:347-404)."""
                victims = []
                total_allocated = self.total_allocated.clone()
                root = self.root.clone(None)

                ljob = ssn.jobs.get(reclaimer.job)
                if ljob is None or ljob.queue not in ssn.queues:
                    return [], PERMIT
                lqueue = ssn.queues[ljob.queue]
                lattr = _DrfAttr(
                    self.job_attrs[ljob.uid].allocated.clone())
                lattr.allocated.add(reclaimer.resreq)
                total_allocated.add(reclaimer.resreq)
                lattr.dominant, lattr.share = _share_of(lattr.allocated,
                                                        self.total)
                self._update_hierarchical_share(
                    root, total_allocated, ljob, lattr, lqueue.hierarchy,
                    lqueue.hierarchical_weights)

                for preemptee in reclaimees:
                    rjob = ssn.jobs.get(preemptee.job)
                    if rjob is None or rjob.queue not in ssn.queues:
                        continue
                    rqueue = ssn.queues[rjob.queue]
                    total_allocated.sub(preemptee.resreq)
                    rattr = _DrfAttr(
                        self.job_attrs[rjob.uid].allocated.clone())
                    rattr.allocated.sub(preemptee.resreq)
                    rattr.dominant, rattr.share = _share_of(rattr.allocated,
                                                            self.total)
                    self._update_hierarchical_share(
                        root, total_allocated, rjob, rattr, rqueue.hierarchy,
                        rqueue.hierarchical_weights)

                    ret = self._compare_queues(root, lqueue, rqueue)

                    total_allocated.add(preemptee.resreq)
                    rattr.allocated.add(preemptee.resreq)
                    rattr.dominant, rattr.share = _share_of(rattr.allocated,
                                                            self.total)
                    self._update_hierarchical_share(
                        root, total_allocated, rjob, rattr, rqueue.hierarchy,
                        rqueue.hierarchical_weights)

                    if ret < 0:
                        victims.append(preemptee)
                return victims, PERMIT

            ssn.add_reclaimable_fn(NAME, reclaimable_fn)

        def job_order_fn(l, r) -> int:
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            return 0 if ls == rs else (-1 if ls < rs else 1)

        ssn.add_job_order_fn(NAME, job_order_fn)

        if ns_enabled:
            def namespace_order_fn(l, r) -> int:
                lo = self.namespace_opts.get(l, _DrfAttr())
                ro = self.namespace_opts.get(r, _DrfAttr())
                li = ssn.namespace_info.get(l)
                ri = ssn.namespace_info.get(r)
                lw = li.get_weight() if li else 1
                rw = ri.get_weight() if ri else 1
                lws, rws = lo.share / lw, ro.share / rw
                m.update_namespace_weight(l, lw)
                m.update_namespace_weight(r, rw)
                m.update_namespace_weighted_share(l, lws)
                m.update_namespace_weighted_share(r, rws)
                return 0 if lws == rws else (-1 if lws < rws else 1)

            ssn.add_namespace_order_fn(NAME, namespace_order_fn)

        def _apply_total(job, total, sign):
            """The single share-update body (drf.go:466-511): per-task
            events pass one task's resreq, batched events a whole gang's
            sum — the arithmetic is identical because shares are recomputed
            from the running ``allocated`` aggregate either way."""
            if job is None:
                return
            attr = self.job_attrs.get(job.uid)
            if attr is None:
                return
            if sign > 0:
                attr.allocated.add(total)
            else:
                attr.allocated.sub(total)
            attr.version += 1
            attr.dominant, attr.share = _share_of(attr.allocated, self.total)
            # job/namespace share gauges are swept once at session close,
            # restricted to jobs an event actually touched
            self._touched_jobs.add(job.uid)
            if ns_enabled:
                ns = self.namespace_opts.setdefault(job.namespace, _DrfAttr())
                if sign > 0:
                    ns.allocated.add(total)
                else:
                    ns.allocated.sub(total)
                ns.dominant, ns.share = _share_of(ns.allocated, self.total)
            if hier_enabled and job.queue in ssn.queues:
                queue = ssn.queues[job.queue]
                if sign > 0:
                    self.total_allocated.add(total)
                else:
                    self.total_allocated.sub(total)
                self._update_hierarchical_share(
                    self.root, self.total_allocated, job, attr,
                    queue.hierarchy, queue.hierarchical_weights)

        ssn.add_event_handler(EventHandler(
            allocate_func=lambda e:
                _apply_total(ssn.jobs.get(e.task.job), e.task.resreq, +1),
            deallocate_func=lambda e:
                _apply_total(ssn.jobs.get(e.task.job), e.task.resreq, -1),
            batch_allocate_func=lambda job, tasks, total:
                _apply_total(job, total, +1),
            batch_deallocate_func=lambda job, tasks, total:
                _apply_total(job, total, -1)))

    # -- share math --------------------------------------------------------

    def _batch_update_shares(self, jobs) -> None:
        """All jobs' (dominant, share) in one kernel call."""
        if not jobs:
            return
        import jax.numpy as jnp

        from ..ops.fairshare import dominant_share

        rindex = ResourceIndex(set(self.total.scalars) | {
            rn for j in jobs
            for rn in self.job_attrs[j.uid].allocated.scalars})
        alloc = np.stack([rindex.vec(self.job_attrs[j.uid].allocated)
                          for j in jobs])
        total = rindex.vec(self.total)
        share, dom = dominant_share(jnp.asarray(alloc), jnp.asarray(total))
        share, dom = np.asarray(share), np.asarray(dom)
        for i, j in enumerate(jobs):
            attr = self.job_attrs[j.uid]
            attr.share = float(share[i])
            attr.dominant = rindex.names[int(dom[i])] if share[i] > 0 else ""

    # -- hierarchical DRF --------------------------------------------------

    def _compare_queues(self, root: _HNode, lqueue, rqueue) -> float:
        """Walk the two hierarchy paths top-down (drf.go:170-200)."""
        lnode, rnode = root, root
        lpaths = lqueue.hierarchy.split("/")
        rpaths = rqueue.hierarchy.split("/")
        depth = min(len(lpaths), len(rpaths))
        for i in range(depth):
            if lnode is None or rnode is None:
                return 0.0
            if not lnode.saturated and rnode.saturated:
                return -1.0
            if lnode.saturated and not rnode.saturated:
                return 1.0
            lv = lnode.attr.share / lnode.weight
            rv = rnode.attr.share / rnode.weight
            if lv == rv:
                if i < depth - 1:
                    lnode = (lnode.children or {}).get(lpaths[i + 1])
                    rnode = (rnode.children or {}).get(rpaths[i + 1])
            else:
                return lv - rv
        return 0.0

    def _build_hierarchy(self, root: _HNode, job, attr: _DrfAttr,
                         hierarchy: str, weights: str) -> None:
        """Insert/refresh the job's leaf under its queue path
        (drf.go:529-568)."""
        inode = root
        paths = hierarchy.split("/")
        wparts = weights.split("/")
        for i in range(1, len(paths)):
            child = inode.children.get(paths[i])
            if child is None:
                try:
                    fweight = float(wparts[i])
                except (IndexError, ValueError):
                    fweight = 1.0
                fweight = max(fweight, 1.0)
                child = _HNode(paths[i], fweight)
                child.parent = inode
                inode.children[paths[i]] = child
            inode = child
        leaf = _HNode(job.uid, 1.0, attr, leaf=True)
        leaf.request = job.total_request.clone()
        leaf.parent = inode
        inode.children[job.uid] = leaf

    def _update_tree(self, node: _HNode, demanding: Dict[str, bool]) -> None:
        """Bottom-up share recomputation with min-dominant-share scaling
        (drf.go:572-617)."""
        if node.children is None:
            node.saturated = _resource_saturated(node.attr.allocated,
                                                 node.request, demanding)
            return
        mdr = 1.0
        for child in node.children.values():
            self._update_tree(child, demanding)
            if child.attr.share != 0 and not child.saturated:
                _, res_share = _share_of(child.attr.allocated, self.total)
                if res_share < mdr:
                    mdr = res_share
        node.attr.allocated = Resource()
        saturated = True
        for child in node.children.values():
            if not child.saturated:
                saturated = False
            if child.attr.share != 0:
                if child.saturated:
                    node.attr.allocated.add(child.attr.allocated)
                else:
                    node.attr.allocated.add(
                        child.attr.allocated.clone().multi(
                            mdr / child.attr.share))
        node.attr.dominant, node.attr.share = _share_of(node.attr.allocated,
                                                        self.total)
        node.saturated = saturated

    def _update_hierarchical_share(self, root: _HNode,
                                   total_allocated: Resource, job,
                                   attr: _DrfAttr, hierarchy: str,
                                   weights: str) -> None:
        if not hierarchy:
            hierarchy, weights = "root", "1"
        demanding: Dict[str, bool] = {}
        for rn in self.total.resource_names():
            if total_allocated.get(rn) < self.total.get(rn):
                demanding[rn] = True
        self._build_hierarchy(root, job, attr, hierarchy, weights)
        self._update_tree(root, demanding)

    def on_session_close(self, ssn) -> None:
        for uid in self._touched_jobs:
            attr = self.job_attrs.get(uid)
            job = ssn.jobs.get(uid)
            if attr is not None and job is not None:
                m.update_job_share(job.namespace, job.name, attr.share)
        self._touched_jobs = set()
        for ns, attr in self.namespace_opts.items():
            m.update_namespace_share(ns, attr.share)
        self.total = Resource()
        self.total_allocated = Resource()
        self.job_attrs = {}
        self.namespace_opts = {}
        self.root = _HNode("root", 1.0)


register_plugin_builder(NAME, DrfPlugin)
