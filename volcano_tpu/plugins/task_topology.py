"""task-topology plugin (reference: pkg/scheduler/plugins/task-topology/
{topology,manager,bucket,util}.go).

Affinity/anti-affinity between task *types* within a job, read from
PodGroup annotations (volcano.sh/task-topology-affinity,
-anti-affinity, -task-order; "a,b;c" -> [[a,b],[c]]):

* buckets are greedily constructed per job, most-constrained tasks first
  (manager.go:266-319);
* TaskOrder interleaves buckets: bucketed before bucketless, bigger
  buckets first, same-bucket ties by affinity priority (topology.go:51-132);
* node score counts the task's bucket-mates already bound to the node,
  penalized by anti-affinity and by bucket overflow beyond the node's
  idle+releasing (topology.go:134-201), normalized by the job's max bucket
  size x plugin weight;
* allocate events migrate tasks from bucket pending-sets to per-node bound
  counts (topology.go:203-211, bucket.go:102-109).

Scores reach the placement kernel through a solver static-score fn that
re-reads the live bucket state at every ``place()`` call, so phase-level
placements see fresh bound counts (in-scan drift within one gang batch is
the accepted approximation of the reference's per-task rescoring).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from ..framework.arguments import Arguments
from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import EventHandler
from ..models.job_info import TaskStatus
from ..models.objects import TASK_SPEC_KEY
from ..models.resource import ZERO

NAME = "task-topology"

PLUGIN_WEIGHT = "task-topology.weight"
AFFINITY_ANNOTATION = "volcano.sh/task-topology-affinity"
ANTI_AFFINITY_ANNOTATION = "volcano.sh/task-topology-anti-affinity"
TASK_ORDER_ANNOTATION = "volcano.sh/task-topology-task-order"
OUT_OF_BUCKET = -1
MAX_NODE_SCORE = 100.0

# topology type -> priority (manager.go:40-46)
PRIO_SELF_ANTI_AFFINITY = 4
PRIO_INTER_AFFINITY = 3
PRIO_SELF_AFFINITY = 2
PRIO_INTER_ANTI_AFFINITY = 1


def get_task_name(task) -> str:
    return task.pod.metadata.annotations.get(TASK_SPEC_KEY, "")


def _req_score(res) -> float:
    """1 milli-cpu == 1 Mi == 1 scalar milli-unit (bucket.go:63-74)."""
    return (res.milli_cpu + res.memory / 1024 / 1024
            + sum(res.scalars.values()))


class Bucket:
    def __init__(self, index: int):
        self.index = index
        self.tasks: Dict[str, object] = {}       # uid -> TaskInfo (pending)
        self.task_name_set: Dict[str, int] = {}
        self.req_score = 0.0
        self.request = None                       # lazily cloned Resource
        self.bound_task = 0
        self.node: Dict[str, int] = {}            # node -> bound count

    def add_task(self, task_name: str, task) -> None:
        self.task_name_set[task_name] = self.task_name_set.get(task_name, 0) + 1
        if task.node_name:
            self.node[task.node_name] = self.node.get(task.node_name, 0) + 1
            self.bound_task += 1
            return
        self.tasks[task.uid] = task
        self.req_score += _req_score(task.resreq)
        if self.request is None:
            self.request = task.resreq.clone()
        else:
            self.request.add(task.resreq)

    def task_bound(self, task) -> None:
        self.node[task.node_name] = self.node.get(task.node_name, 0) + 1
        self.bound_task += 1
        if task.uid in self.tasks:
            del self.tasks[task.uid]
            self.req_score -= _req_score(task.resreq)
            if self.request is not None:
                for name in task.resreq.resource_names():
                    self.request.set(name, max(
                        0.0, self.request.get(name) - task.resreq.get(name)))


class JobManager:
    def __init__(self, job_uid: str):
        self.job_uid = job_uid
        self.buckets: List[Bucket] = []
        self.pod_in_bucket: Dict[str, int] = {}
        self.pod_in_task: Dict[str, str] = {}
        self.task_affinity_priority: Dict[str, int] = {}
        self.task_exist_order: Dict[str, int] = {}
        self.inter_affinity: Dict[str, Set[str]] = {}
        self.self_affinity: Set[str] = set()
        self.inter_anti_affinity: Dict[str, Set[str]] = {}
        self.self_anti_affinity: Set[str] = set()
        self.bucket_max_size = 0
        self.node_task_set: Dict[str, Dict[str, int]] = {}

    # -- topology ingestion (manager.go:103-150) ---------------------------

    def _mark(self, task_name: str, priority: int) -> None:
        if priority > self.task_affinity_priority.get(task_name, 0):
            self.task_affinity_priority[task_name] = priority

    def apply_task_topology(self, affinity, anti_affinity, task_order) -> None:
        for aff in affinity or []:
            if len(aff) == 1:
                self.self_affinity.add(aff[0])
                self._mark(aff[0], PRIO_SELF_AFFINITY)
                continue
            for i, src in enumerate(aff):
                for dst in aff[:i]:
                    self.inter_affinity.setdefault(src, set()).add(dst)
                    self.inter_affinity.setdefault(dst, set()).add(src)
                self._mark(src, PRIO_INTER_AFFINITY)
        for aff in anti_affinity or []:
            if len(aff) == 1:
                self.self_anti_affinity.add(aff[0])
                self._mark(aff[0], PRIO_SELF_ANTI_AFFINITY)
                continue
            for i, src in enumerate(aff):
                for dst in aff[:i]:
                    self.inter_anti_affinity.setdefault(src, set()).add(dst)
                    self.inter_anti_affinity.setdefault(dst, set()).add(src)
                self._mark(src, PRIO_INTER_ANTI_AFFINITY)
        order = task_order or []
        for i, task_name in enumerate(order):
            self.task_exist_order[task_name] = len(order) - i

    # -- bucket construction (manager.go:203-319) --------------------------

    def task_affinity_order(self, l, r) -> int:
        lname = self.pod_in_task.get(l.uid, "")
        rname = self.pod_in_task.get(r.uid, "")
        if lname == rname:
            return 0
        lo = self.task_exist_order.get(lname, 0)
        ro = self.task_exist_order.get(rname, 0)
        if lo != ro:
            return 1 if lo > ro else -1
        lp = self.task_affinity_priority.get(lname, 0)
        rp = self.task_affinity_priority.get(rname, 0)
        if lp != rp:
            return 1 if lp > rp else -1
        return 0

    def check_task_set_affinity(self, task_name: str,
                                task_name_set: Dict[str, int],
                                only_anti: bool) -> int:
        score = 0
        if not task_name:
            return score
        for name_in_bucket, count in task_name_set.items():
            same = name_in_bucket == task_name
            if not only_anti:
                affinity = (task_name in self.self_affinity) if same else \
                    (name_in_bucket in self.inter_affinity.get(task_name, ()))
                if affinity:
                    score += count
            anti = (task_name in self.self_anti_affinity) if same else \
                (name_in_bucket in self.inter_anti_affinity.get(task_name, ()))
            if anti:
                score -= count
        return score

    def construct_buckets(self, tasks: Dict[str, object]) -> None:
        import functools
        without_bucket = []
        for task in tasks.values():
            task_name = get_task_name(task)
            if not task_name or task_name not in self.task_affinity_priority:
                self.pod_in_bucket[task.uid] = OUT_OF_BUCKET
                continue
            self.pod_in_task[task.uid] = task_name
            without_bucket.append(task)

        def order(l, r):
            """Bound tasks first, then by affinity order descending
            (util.go:88-119 reversed)."""
            lb, rb = bool(l.node_name), bool(r.node_name)
            if lb or rb:
                if lb != rb:
                    return -1 if lb else 1
                return -1 if l.node_name > r.node_name else 1
            v = self.task_affinity_order(l, r)
            if v == 0:
                return -1 if l.name > r.name else 1
            return -v

        without_bucket.sort(key=functools.cmp_to_key(order))
        self._build_buckets(without_bucket)

    def _build_buckets(self, ordered) -> None:
        node_bucket: Dict[str, Bucket] = {}
        for task in ordered:
            task_name = get_task_name(task)
            selected: Optional[Bucket] = None
            max_affinity = -(2 ** 31)
            if task.node_name:
                max_affinity = 0
                selected = node_bucket.get(task.node_name)
            else:
                for bucket in self.buckets:
                    aff = self.check_task_set_affinity(
                        task_name, bucket.task_name_set, False)
                    if aff > max_affinity:
                        max_affinity = aff
                        selected = bucket
                    elif (aff == max_affinity and selected is not None
                          and bucket.req_score < selected.req_score):
                        selected = bucket
            if max_affinity < 0 or selected is None:
                selected = Bucket(len(self.buckets))
                self.buckets.append(selected)
                if task.node_name:
                    node_bucket[task.node_name] = selected
            self.pod_in_bucket[task.uid] = selected.index
            selected.add_task(task_name, task)
            size = len(selected.tasks) + selected.bound_task
            if size > self.bucket_max_size:
                self.bucket_max_size = size

    def get_bucket(self, task) -> Optional[Bucket]:
        idx = self.pod_in_bucket.get(task.uid, OUT_OF_BUCKET)
        if idx == OUT_OF_BUCKET:
            return None
        return self.buckets[idx]

    def task_bound(self, task) -> None:
        task_name = get_task_name(task)
        if task_name:
            self.node_task_set.setdefault(task.node_name, {})
            s = self.node_task_set[task.node_name]
            s[task_name] = s.get(task_name, 0) + 1
        bucket = self.get_bucket(task)
        if bucket is not None:
            bucket.task_bound(task)


def parse_affinity_annotation(raw: Optional[str],
                              valid_names: Set[str]) -> Optional[List[List[str]]]:
    """"a,b;c" -> [[a, b], [c]], validated against the job's task-spec names
    (topology.go:239-287; validation keys off TaskSpecKey annotations rather
    than the reference's pod-name parsing)."""
    if raw is None:
        return None
    groups = []
    for part in str(raw).split(";"):
        names = [n for n in (x.strip() for x in part.split(",")) if n]
        if not names:
            continue
        seen = set()
        for n in names:
            if n not in valid_names or n in seen:
                return None
            seen.add(n)
        groups.append(names)
    return groups or None


class TaskTopologyPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = Arguments(arguments or {})
        self.weight = self.arguments.get_int(PLUGIN_WEIGHT, 1)
        self.managers: Dict[str, JobManager] = {}

    def name(self) -> str:
        return NAME

    # -- session wiring ----------------------------------------------------

    def _init_buckets(self, ssn) -> None:
        for uid, job in ssn.jobs.items():
            if not job.task_status_index.get(TaskStatus.Pending, {}):
                continue
            if job.pod_group is None:
                continue
            ann = job.pod_group.metadata.annotations
            raws = (ann.get(AFFINITY_ANNOTATION),
                    ann.get(ANTI_AFFINITY_ANNOTATION),
                    ann.get(TASK_ORDER_ANNOTATION))
            if all(r is None for r in raws):
                continue
            valid = {get_task_name(t) for t in job.tasks.values()} - {""}
            # any present-but-invalid annotation aborts the whole job's
            # topology (topology.go:289-334 returns error on any parse
            # failure)
            affinity = anti = order = None
            invalid = False
            if raws[0] is not None:
                affinity = parse_affinity_annotation(raws[0], valid)
                invalid |= affinity is None
            if raws[1] is not None:
                anti = parse_affinity_annotation(raws[1], valid)
                invalid |= anti is None
            if raws[2] is not None:
                parsed = parse_affinity_annotation(raws[2], valid)
                if parsed:
                    order = [n for grp in parsed for n in grp]
                else:
                    invalid = True
            if invalid:
                continue
            manager = JobManager(uid)
            manager.apply_task_topology(affinity, anti, order)
            manager.construct_buckets(job.tasks)
            self.managers[uid] = manager

    def task_order_fn(self, l, r) -> int:
        """Interleave: bucketed < bucketless; bigger bucket first; older
        bucket first; same bucket by affinity order (topology.go:51-132)."""
        lm, rm = self.managers.get(l.job), self.managers.get(r.job)
        if lm is None or rm is None:
            return 0
        lb, rb = lm.get_bucket(l), rm.get_bucket(r)
        if (lb is not None) != (rb is not None):
            return -1 if lb is not None else 1
        if l.job != r.job:
            return 0
        if lb is None and rb is None:
            return 0
        if len(lb.tasks) != len(rb.tasks):
            return -1 if len(lb.tasks) > len(rb.tasks) else 1
        if lb.index == rb.index:
            return -lm.task_affinity_order(l, r)
        return -1 if lb.index < rb.index else 1

    def calc_bucket_score(self, task, node) -> tuple:
        """(score, manager) for one task x node (topology.go:134-187)."""
        max_resource = node.idle.clone().add(node.releasing)
        if task.resreq is not None and \
                max_resource.less_partly(task.resreq, ZERO):
            return 0, None
        manager = self.managers.get(task.job)
        if manager is None:
            return 0, None
        bucket = manager.get_bucket(task)
        if bucket is None:
            return 0, manager
        score = bucket.node.get(node.name, 0)
        node_task_set = manager.node_task_set.get(node.name)
        if node_task_set:
            aff = manager.check_task_set_affinity(
                get_task_name(task), node_task_set, True)
            if aff < 0:
                score += aff
        score += len(bucket.tasks)
        if bucket.request is None or bucket.request.less_equal(max_resource,
                                                               ZERO):
            return score, manager
        remains = bucket.request.clone()
        for uid, btask in bucket.tasks.items():
            if uid == task.uid or btask.resreq is None:
                continue
            for name in btask.resreq.resource_names():
                remains.set(name, max(0.0, remains.get(name)
                                      - btask.resreq.get(name)))
            score -= 1
            if remains.less_equal(max_resource, ZERO):
                break
        return score, manager

    def node_order_fn(self, task, node) -> float:
        score, manager = self.calc_bucket_score(task, node)
        fscore = float(score * self.weight)
        if manager is not None and manager.bucket_max_size != 0:
            fscore = fscore * MAX_NODE_SCORE / manager.bucket_max_size
        return fscore

    def _vector_scores(self, ssn, batch, narr) -> np.ndarray:
        """calc_bucket_score over all (group, node) pairs as numpy array
        math: bound-mate counts and anti-affinity penalties are scattered
        from the (small) bucket dicts, the bucket-overflow reduction is a
        cumsum/argmax over bucket mates — no per-node Python scoring."""
        rindex = ssn.solver.rindex
        n_pad = narr.idle.shape[0]
        if not self.managers:
            return None   # pass-through (no dense [G,N] transfer)
        relevant = [(g, batch.tasks[m[0]]) for g, m in
                    enumerate(batch.group_members)
                    if batch.tasks[m[0]].job in self.managers]
        if not relevant:
            return None
        out = np.zeros((batch.g_pad, n_pad), np.float32)
        # idle + releasing per node (topology.go:136), one host pass
        max_res = np.zeros((n_pad, rindex.r), np.float32)
        for i, name in enumerate(narr.names):
            node = ssn.nodes.get(name)
            if node is not None:
                max_res[i] = (rindex.vec(node.idle)
                              + rindex.vec(node.releasing))
        eps = rindex.eps
        for g, rep in relevant:
            manager = self.managers[rep.job]
            bucket = manager.get_bucket(rep)
            if bucket is None:
                continue
            req = rindex.vec(rep.resreq)
            prefit_ok = ~np.any(max_res + eps[None, :] < req[None, :], axis=1)
            score = np.zeros(n_pad, np.float32)
            for node_name, cnt in bucket.node.items():
                i = narr.name_to_idx.get(node_name)
                if i is not None:
                    score[i] += cnt
            task_name = get_task_name(rep)
            for node_name, tset in manager.node_task_set.items():
                i = narr.name_to_idx.get(node_name)
                if i is None:
                    continue
                aff = manager.check_task_set_affinity(task_name, tset, True)
                if aff < 0:
                    score[i] += aff
            score += len(bucket.tasks)
            if bucket.request is not None:
                # evict mates from the virtual bucket until it fits each
                # node: cumsum + first-fit argmax (topology.go:166-186)
                breq = rindex.vec(bucket.request)
                mates = [t for uid, t in bucket.tasks.items()
                         if uid != rep.uid and t.resreq is not None]
                mres = (np.stack([rindex.vec(t.resreq) for t in mates])
                        if mates else np.zeros((0, rindex.r), np.float32))
                cum = np.concatenate(
                    [np.zeros((1, rindex.r), np.float32),
                     np.cumsum(mres, axis=0)], axis=0)        # [V+1, R]
                rem = breq[None, :] - cum                      # [V+1, R]
                fits = np.all(rem[None, :, :] <= max_res[:, None, :]
                              + eps[None, None, :], axis=2)    # [N, V+1]
                kmin = np.argmax(fits, axis=1)
                k = np.where(np.any(fits, axis=1), kmin, len(mates))
                score = score - k
            fscore = score * float(self.weight)
            if manager.bucket_max_size:
                fscore = fscore * MAX_NODE_SCORE / manager.bucket_max_size
            out[g] = np.where(prefit_ok, fscore, 0.0)
        return out

    def on_session_open(self, ssn) -> None:
        self._init_buckets(ssn)
        ssn.add_task_order_fn(NAME, self.task_order_fn)
        ssn.add_node_order_fn(NAME, self.node_order_fn)

        def allocate_fn(event):
            manager = self.managers.get(event.task.job)
            if manager is not None:
                manager.task_bound(event.task)

        ssn.add_event_handler(EventHandler(allocate_func=allocate_fn))

        if ssn.solver is not None and ssn.plugin_enabled(NAME,
                                                         "enabledNodeOrder"):
            def score_fn(batch, narr, feats):
                return self._vector_scores(ssn, batch, narr)
            ssn.solver.add_static_score_fn(score_fn)

            def bucket_fn(task):
                """Same-bucket mates attract inside the scan: per-mate bonus
                mirrors one bound bucket mate's worth of node score."""
                manager = self.managers.get(task.job)
                if manager is None:
                    return None
                bucket = manager.get_bucket(task)
                if bucket is None:
                    return None
                bonus = float(self.weight)
                if manager.bucket_max_size:
                    bonus = bonus * MAX_NODE_SCORE / manager.bucket_max_size
                return (task.job, bucket.index), bonus
            ssn.solver.set_bucket_fn(bucket_fn)

    def on_session_close(self, ssn) -> None:
        self.managers = {}


register_plugin_builder(NAME, TaskTopologyPlugin)
