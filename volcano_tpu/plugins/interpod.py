"""Inter-pod affinity/anti-affinity: predicate + batch scorer support.

Reference wiring: the upstream k8s InterPodAffinity plugin runs as a filter
(pkg/scheduler/plugins/predicates/predicates.go:262-341) and as the batch
scorer (pkg/scheduler/plugins/nodeorder/nodeorder.go:271-295). Both
evaluate against the k8s snapshot built once at session open
(plugins/util/k8s.Snapshot) — in-cycle placements are NOT visible to them
in the reference either, so the cycle-static index here is semantically
faithful, not a simplification.

TPU-first shape: topology keys become integer-coded node vectors and each
(pod-affinity term) becomes a set of allowed/blocked topology codes; the
per-group node mask / score vector falls out of `np.isin`-style vector ops
instead of the upstream's per-node pod loops.

Semantics implemented (upstream interpodaffinity):

* required affinity: every term must find >=1 existing pod whose labels
  match the term selector (in the term's namespaces, defaulting to the
  incoming pod's) on a node sharing the candidate node's topology value;
  the self-match bootstrap exception applies (a pod whose own labels match
  the term may found a new topology).
* required anti-affinity: no matching existing pod may share the candidate
  node's topology value; plus existing-pod symmetry — an existing pod with
  a required anti-affinity term matching the incoming pod blocks its own
  topology.
* preferred (anti-)affinity: weighted matches per topology, including the
  symmetric contributions of existing pods' preferred terms, normalized to
  0..100 like the upstream NormalizeScore.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..models.objects import PodAffinityTerm


def _term_matches(term: PodAffinityTerm, labels: Dict[str, str],
                  pod_ns: str, default_ns: str) -> bool:
    """Does a pod (labels, pod_ns) fall under the term's selector+ns?"""
    namespaces = term.namespaces or [default_ns]
    if pod_ns not in namespaces:
        return False
    return all(req.matches(labels) for req in term.label_selector)


class InterPodIndex:
    """Cycle-static index of assigned pods for affinity evaluation.

    ``names`` fixes the node order every returned vector uses (the solver
    passes NodeArrays.names; the host predicate passes the session node
    list — identical ordering by construction).
    """

    def __init__(self, ssn, names: List[str]):
        self.names = list(names)
        self.node_labels: List[Dict[str, str]] = []
        # (labels, ns, node_idx) of every snapshot-assigned pod
        self.pods: List[Tuple[Dict[str, str], str, int]] = []
        # existing pods carrying affinity terms, for symmetry rules:
        # (terms, labels, ns, node_idx)
        self.anti_required: List[Tuple[list, str, int]] = []
        self.pref_terms: List[Tuple[list, str, int, float]] = []
        for i, name in enumerate(self.names):
            node = ssn.nodes.get(name)
            labels = node.node.metadata.labels \
                if node is not None and node.node is not None else {}
            self.node_labels.append(labels)
            if node is None:
                continue
            for t in node.tasks.values():
                pod = t.pod
                self.pods.append((pod.metadata.labels, t.namespace, i))
                aff = pod.spec.affinity
                if aff is None:
                    continue
                if aff.pod_anti_affinity is not None \
                        and aff.pod_anti_affinity.required:
                    self.anti_required.append(
                        (aff.pod_anti_affinity.required, t.namespace, i))
                for wt in ((aff.pod_affinity.preferred
                            if aff.pod_affinity else []) or []):
                    self.pref_terms.append(
                        ([wt.term], t.namespace, i, float(wt.weight)))
                for wt in ((aff.pod_anti_affinity.preferred
                            if aff.pod_anti_affinity else []) or []):
                    self.pref_terms.append(
                        ([wt.term], t.namespace, i, -float(wt.weight)))
        self._topo_codes: Dict[str, np.ndarray] = {}
        self._topo_values: Dict[str, Dict[str, int]] = {}
        # lazy vector encodings over the assigned-pod set: label values and
        # namespaces become integer codes once per cycle, so each term's
        # selector is evaluated on the (tiny) distinct-value vocabulary and
        # applied to all pods with isin/bincount — O(pods) Python sweeps
        # per (term x group) were the round-2 hot spot at 10k nodes
        self._pod_node: Optional[np.ndarray] = None     # [M] node idx
        self._pod_ns: Optional[np.ndarray] = None       # [M] ns code
        self._ns_vocab: Dict[str, int] = {}
        self._pod_label_codes: Dict[str, tuple] = {}    # key -> (codes, vocab)
        self._term_match_cache: Dict[tuple, np.ndarray] = {}
        self._pod_topo_cache: Dict[str, np.ndarray] = {}  # key -> [M] codes

    def topo_codes(self, key: str) -> Tuple[np.ndarray, Dict[str, int]]:
        """[n_real] int topology code per node (-1 = label missing)."""
        cached = self._topo_codes.get(key)
        if cached is not None:
            return cached, self._topo_values[key]
        values: Dict[str, int] = {}
        codes = np.full(len(self.node_labels), -1, np.int32)
        for i, labels in enumerate(self.node_labels):
            v = labels.get(key)
            if v is not None:
                codes[i] = values.setdefault(v, len(values))
        self._topo_codes[key] = codes
        self._topo_values[key] = values
        return codes, values

    # -- vector encodings ----------------------------------------------------

    def _ensure_pod_arrays(self) -> None:
        if self._pod_node is not None:
            return
        m = len(self.pods)
        self._pod_node = np.fromiter((i for _, _, i in self.pods),
                                     np.int64, m)
        ns_codes = np.empty(m, np.int32)
        for p, (_, ns, _) in enumerate(self.pods):
            ns_codes[p] = self._ns_vocab.setdefault(ns, len(self._ns_vocab))
        self._pod_ns = ns_codes

    def _pod_codes(self, key: str) -> tuple:
        """([M] value code per pod (-1 = label absent), value vocab)."""
        cached = self._pod_label_codes.get(key)
        if cached is not None:
            return cached
        self._ensure_pod_arrays()
        vocab: Dict[str, int] = {}
        codes = np.full(len(self.pods), -1, np.int32)
        for p, (labels, _, _) in enumerate(self.pods):
            v = labels.get(key)
            if v is not None:
                codes[p] = vocab.setdefault(v, len(vocab))
        self._pod_label_codes[key] = (codes, vocab)
        return codes, vocab

    @staticmethod
    def _term_signature(term: PodAffinityTerm, namespaces: tuple) -> tuple:
        return (namespaces,
                tuple((r.key, r.operator, tuple(r.values or []))
                      for r in term.label_selector))

    def _term_match(self, term: PodAffinityTerm,
                    default_ns: str) -> np.ndarray:
        """[M] bool: pods the term selects. Semantically identical to
        mapping _term_matches over self.pods — each selector requirement is
        evaluated once per *distinct label value* through the same
        ``req.matches`` oracle, then broadcast by code."""
        self._ensure_pod_arrays()
        namespaces = tuple(term.namespaces or [default_ns])
        sig = self._term_signature(term, namespaces)
        cached = self._term_match_cache.get(sig)
        if cached is not None:
            return cached
        ns_codes = [self._ns_vocab[n] for n in namespaces
                    if n in self._ns_vocab]
        out = np.isin(self._pod_ns, ns_codes) if ns_codes \
            else np.zeros(len(self.pods), bool)
        for req in term.label_selector:
            codes, vocab = self._pod_codes(req.key)
            ok_codes = [c for v, c in vocab.items()
                        if req.matches({req.key: v})]
            if req.matches({}):   # absent-label semantics via the oracle
                ok_codes.append(-1)
            out = out & np.isin(codes, ok_codes)
        self._term_match_cache[sig] = out
        return out

    def _pod_topo(self, key: str) -> np.ndarray:
        """[M] topology code of each pod's node under `key`, cached."""
        pc = self._pod_topo_cache.get(key)
        if pc is None:
            codes, _ = self.topo_codes(key)
            self._ensure_pod_arrays()
            pc = codes[self._pod_node]
            self._pod_topo_cache[key] = pc
        return pc

    def matching_topologies(self, term: PodAffinityTerm,
                            default_ns: str) -> Set[int]:
        """Topology codes (under term.topology_key) hosting >=1 pod the
        term selects."""
        if not self.pods:
            return set()
        pc = self._pod_topo(term.topology_key)
        sel = self._term_match(term, default_ns) & (pc >= 0)
        return {int(c) for c in np.unique(pc[sel])}

    # -- predicate ---------------------------------------------------------

    def required_mask(self, task) -> Optional[np.ndarray]:
        """[n_real] bool for the task's required (anti-)affinity incl. the
        existing-pod symmetry rule; None when nothing applies."""
        aff = task.pod.spec.affinity
        pod_labels = task.pod.metadata.labels
        ns = task.namespace
        n = len(self.node_labels)
        mask: Optional[np.ndarray] = None

        terms = (aff.pod_affinity.required
                 if aff is not None and aff.pod_affinity is not None else [])
        for term in terms:
            codes, _ = self.topo_codes(term.topology_key)
            allowed = self.matching_topologies(term, ns)
            if not allowed:
                # bootstrap: the pod's own labels satisfy the term — any
                # node with the topology label may found the group
                if _term_matches(term, pod_labels, ns, ns):
                    ok = codes >= 0
                else:
                    ok = np.zeros(n, bool)
            else:
                ok = np.isin(codes, list(allowed))
            mask = ok if mask is None else (mask & ok)

        anti = (aff.pod_anti_affinity.required
                if aff is not None and aff.pod_anti_affinity is not None
                else [])
        for term in anti:
            codes, _ = self.topo_codes(term.topology_key)
            blocked = self.matching_topologies(term, ns)
            if blocked:
                ok = ~np.isin(codes, list(blocked))
                mask = ok if mask is None else (mask & ok)

        # symmetry: existing pods' required anti-affinity blocks the
        # incoming pod on their topology when it matches their terms
        for terms_e, ns_e, i in self.anti_required:
            for term in terms_e:
                if not _term_matches(term, pod_labels, ns, ns_e):
                    continue
                codes, _ = self.topo_codes(term.topology_key)
                c = codes[i]
                if c >= 0:
                    ok = codes != c
                    mask = ok if mask is None else (mask & ok)
        return mask

    # -- batch scorer ------------------------------------------------------

    def preference_score(self, task) -> Optional[np.ndarray]:
        """[n_real] float raw preferred-affinity score (pre-normalization),
        including symmetric contributions; None when nothing applies."""
        aff = task.pod.spec.affinity
        pod_labels = task.pod.metadata.labels
        ns = task.namespace
        n = len(self.node_labels)
        raw = np.zeros(n, np.float64)
        touched = False

        pref = (aff.pod_affinity.preferred
                if aff is not None and aff.pod_affinity is not None else [])
        anti_pref = (aff.pod_anti_affinity.preferred
                     if aff is not None and aff.pod_anti_affinity is not None
                     else [])
        for weighted, sign in ((pref, 1.0), (anti_pref, -1.0)):
            for wt in weighted:
                term = wt.term
                codes, values = self.topo_codes(term.topology_key)
                pc = self._pod_topo(term.topology_key)
                sel = self._term_match(term, ns) & (pc >= 0)
                if sel.any():
                    touched = True
                    counts = np.bincount(pc[sel],
                                         minlength=max(1, len(values)))
                    raw += sign * wt.weight * np.where(
                        codes >= 0, counts[np.maximum(codes, 0)], 0)

        # symmetry: existing pods' preferred terms toward the incoming pod
        for terms_e, ns_e, i, w in self.pref_terms:
            for term in terms_e:
                if not _term_matches(term, pod_labels, ns, ns_e):
                    continue
                codes, _ = self.topo_codes(term.topology_key)
                c = codes[i]
                if c >= 0:
                    touched = True
                    raw[codes == c] += w
        return raw if touched else None


def normalize(raw: np.ndarray, weight: float) -> np.ndarray:
    """Upstream NormalizeScore: linear map of [min, max] onto [0, 100]."""
    lo, hi = float(raw.min()), float(raw.max())
    if hi <= lo:
        return np.zeros_like(raw, np.float32)
    return ((raw - lo) / (hi - lo) * 100.0 * weight).astype(np.float32)


def task_has_pod_affinity(task) -> bool:
    aff = task.pod.spec.affinity
    if aff is None:
        return False
    return ((aff.pod_affinity is not None
             and bool(aff.pod_affinity.required
                      or aff.pod_affinity.preferred))
            or (aff.pod_anti_affinity is not None
                and bool(aff.pod_anti_affinity.required
                         or aff.pod_anti_affinity.preferred)))


def get_index(ssn, names: List[str]) -> InterPodIndex:
    """Session-cached index (assignments are cycle-static, see module
    docstring)."""
    cached = getattr(ssn, "_interpod_index", None)
    if cached is not None and cached.names == list(names):
        return cached
    index = InterPodIndex(ssn, names)
    ssn._interpod_index = index
    return index
