"""Builtin scheduler plugins (reference: pkg/scheduler/plugins/factory.go:
37-56). Importing this package registers all builders."""

from . import binpack  # noqa: F401
from . import conformance  # noqa: F401
from . import drf  # noqa: F401
from . import gang  # noqa: F401
from . import proportion  # noqa: F401
from . import nodeorder  # noqa: F401
from . import overcommit  # noqa: F401
from . import sla  # noqa: F401
from . import numaaware  # noqa: F401
from . import task_topology  # noqa: F401
from . import tdm  # noqa: F401
from . import predicates  # noqa: F401
from . import priority  # noqa: F401
from . import reservation  # noqa: F401
