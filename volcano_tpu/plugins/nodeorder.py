"""nodeorder plugin (reference: pkg/scheduler/plugins/nodeorder/
nodeorder.go).

Weighted sum of the standard k8s scorers: LeastRequested, MostRequested,
BalancedResourceAllocation, NodeAffinity (preferred terms), TaintToleration
(PreferNoSchedule) -- weights from arguments (nodeorder.go:39-135):

    leastrequested.weight    (default 1)
    mostrequested.weight     (default 0)
    balancedresource.weight  (default 1)
    nodeaffinity.weight      (default 1)
    tainttoleration.weight   (default 1)
    podaffinity.weight       (default 1; batch scorer, see interpod module)

TPU-first: least/most/balanced run inside the allocate scan (dynamic state);
nodeaffinity-preferred and PreferNoSchedule taints are cycle-static, so they
are encoded per group x node once and added as a static score term.
"""

from __future__ import annotations

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder

NAME = "nodeorder"


def _preferred_affinity_score(task, labels) -> float:
    aff = task.pod.spec.affinity
    if aff is None or aff.node_affinity is None:
        return 0.0
    total = 0.0
    max_total = 0.0
    for pref in aff.node_affinity.preferred:
        max_total += pref.weight
        if pref.preference.matches(labels):
            total += pref.weight
    if max_total <= 0:
        return 0.0
    return total / max_total * 100.0


def _prefer_no_schedule_score(task, node) -> float:
    """Fewer untolerated PreferNoSchedule taints -> higher score."""
    if node.node is None:
        return 100.0
    intolerable = 0
    total = 0
    for taint in node.node.spec.taints:
        if taint.effect != "PreferNoSchedule":
            continue
        total += 1
        if not any(tol.tolerates(taint) for tol in task.pod.spec.tolerations):
            intolerable += 1
    if total == 0:
        return 100.0
    return (1.0 - intolerable / total) * 100.0


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments=None):
        args = arguments or {}
        get = args.get_int if hasattr(args, "get_int") else \
            (lambda k, d: int(args.get(k, d)))
        self.least_w = get("leastrequested.weight", 1)
        self.most_w = get("mostrequested.weight", 0)
        self.balanced_w = get("balancedresource.weight", 1)
        self.node_affinity_w = get("nodeaffinity.weight", 1)
        self.taint_w = get("tainttoleration.weight", 1)

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        if ssn.solver is not None and ssn.plugin_enabled(NAME, "enabledNodeOrder"):
            ssn.solver.add_weight("least", float(self.least_w))
            ssn.solver.add_weight("most", float(self.most_w))
            ssn.solver.add_weight("balanced", float(self.balanced_w))
            ssn.solver.mark_vectorized(NAME)
            if self.node_affinity_w or self.taint_w:
                ssn.solver.add_static_score_fn(self._static_score(ssn))

        def node_order_fn(task, node) -> float:
            """Host-side mirror for single-pair paths."""
            score = 0.0
            alloc = node.allocatable
            used = node.used
            if alloc.milli_cpu > 0 and alloc.memory > 0:
                cpu_frac = min(1.0, (used.milli_cpu + task.resreq.milli_cpu) / alloc.milli_cpu)
                mem_frac = min(1.0, (used.memory + task.resreq.memory) / alloc.memory)
                score += self.least_w * (((1 - cpu_frac) + (1 - mem_frac)) / 2) * 100
                score += self.most_w * ((cpu_frac + mem_frac) / 2) * 100
                score += self.balanced_w * (100 - abs(cpu_frac - mem_frac) * 100)
            labels = node.node.metadata.labels if node.node is not None else {}
            score += self.node_affinity_w * _preferred_affinity_score(task, labels)
            score += self.taint_w * _prefer_no_schedule_score(task, node)
            return score

        ssn.add_node_order_fn(NAME, node_order_fn)

    def _static_score(self, ssn):
        def fn(batch, narr, feats):
            score = np.zeros((batch.g_pad, narr.n_pad), np.float32)
            # PreferNoSchedule taints are rare: sweep only nodes that carry
            # one (taint-free nodes score a constant, which can't change the
            # per-task argmax and is omitted)
            taint_nodes = [
                (name, i) for name, i in narr.name_to_idx.items()
                if ssn.nodes[name].node is not None
                and any(t.effect == "PreferNoSchedule"
                        for t in ssn.nodes[name].node.spec.taints)]
            for g, members in enumerate(batch.group_members):
                rep = batch.tasks[members[0]]
                has_pref = (rep.pod.spec.affinity is not None
                            and rep.pod.spec.affinity.node_affinity is not None
                            and rep.pod.spec.affinity.node_affinity.preferred)
                if has_pref and self.node_affinity_w:
                    for name, i in narr.name_to_idx.items():
                        labels = ssn.nodes[name].node.metadata.labels \
                            if ssn.nodes[name].node else {}
                        score[g, i] += self.node_affinity_w * \
                            _preferred_affinity_score(rep, labels)
                if self.taint_w:
                    for name, i in taint_nodes:
                        # relative to the taint-free constant of 100
                        score[g, i] += self.taint_w * (
                            _prefer_no_schedule_score(rep, ssn.nodes[name]) - 100.0)
            return score
        return fn


register_plugin_builder(NAME, NodeOrderPlugin)
