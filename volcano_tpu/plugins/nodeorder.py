"""nodeorder plugin (reference: pkg/scheduler/plugins/nodeorder/
nodeorder.go).

Weighted sum of the standard k8s scorers: LeastRequested, MostRequested,
BalancedResourceAllocation, NodeAffinity (preferred terms), TaintToleration
(PreferNoSchedule) -- weights from arguments (nodeorder.go:39-135):

    leastrequested.weight    (default 1)
    mostrequested.weight     (default 0)
    balancedresource.weight  (default 1)
    nodeaffinity.weight      (default 1)
    tainttoleration.weight   (default 1)
    podaffinity.weight       (default 1)

TPU-first: least/most/balanced run inside the allocate scan (dynamic state);
nodeaffinity-preferred, PreferNoSchedule taints and inter-pod preferred
affinity (the reference's BatchNodeOrder scorer, nodeorder.go:271-295 —
evaluated against the session-open snapshot there too, so cycle-static is
exact; plugins/interpod.py) are encoded per group x node once and added as
a static score term.
"""

from __future__ import annotations

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder

NAME = "nodeorder"


def _preferred_affinity_score(task, labels) -> float:
    aff = task.pod.spec.affinity
    if aff is None or aff.node_affinity is None:
        return 0.0
    total = 0.0
    max_total = 0.0
    for pref in aff.node_affinity.preferred:
        max_total += pref.weight
        if pref.preference.matches(labels):
            total += pref.weight
    if max_total <= 0:
        return 0.0
    return total / max_total * 100.0


def _prefer_no_schedule_score(task, node) -> float:
    """Fewer untolerated PreferNoSchedule taints -> higher score."""
    if node.node is None:
        return 100.0
    intolerable = 0
    total = 0
    for taint in node.node.spec.taints:
        if taint.effect != "PreferNoSchedule":
            continue
        total += 1
        if not any(tol.tolerates(taint) for tol in task.pod.spec.tolerations):
            intolerable += 1
    if total == 0:
        return 100.0
    return (1.0 - intolerable / total) * 100.0


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments=None):
        args = arguments or {}
        get = args.get_int if hasattr(args, "get_int") else \
            (lambda k, d: int(args.get(k, d)))
        self.least_w = get("leastrequested.weight", 1)
        self.most_w = get("mostrequested.weight", 0)
        self.balanced_w = get("balancedresource.weight", 1)
        self.node_affinity_w = get("nodeaffinity.weight", 1)
        self.taint_w = get("tainttoleration.weight", 1)
        self.pod_affinity_w = get("podaffinity.weight", 1)

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        if ssn.solver is not None and ssn.plugin_enabled(NAME, "enabledNodeOrder"):
            ssn.solver.add_weight("least", float(self.least_w))
            ssn.solver.add_weight("most", float(self.most_w))
            ssn.solver.add_weight("balanced", float(self.balanced_w))
            ssn.solver.mark_vectorized(NAME)
            if self.node_affinity_w or self.taint_w:
                ssn.solver.add_static_score_fn(self._static_score(ssn))

        def node_order_fn(task, node) -> float:
            """Host-side mirror for single-pair paths."""
            score = 0.0
            alloc = node.allocatable
            used = node.used
            if alloc.milli_cpu > 0 and alloc.memory > 0:
                cpu_frac = min(1.0, (used.milli_cpu + task.resreq.milli_cpu) / alloc.milli_cpu)
                mem_frac = min(1.0, (used.memory + task.resreq.memory) / alloc.memory)
                score += self.least_w * (((1 - cpu_frac) + (1 - mem_frac)) / 2) * 100
                score += self.most_w * ((cpu_frac + mem_frac) / 2) * 100
                score += self.balanced_w * (100 - abs(cpu_frac - mem_frac) * 100)
            labels = node.node.metadata.labels if node.node is not None else {}
            score += self.node_affinity_w * _preferred_affinity_score(task, labels)
            score += self.taint_w * _prefer_no_schedule_score(task, node)
            return score

        ssn.add_node_order_fn(NAME, node_order_fn)

        def batch_node_order_fn(task, nodes):
            """Inter-pod preferred affinity over a node set (the
            reference's BatchNodeOrderFn, nodeorder.go:278-300)."""
            from . import interpod
            if not self.pod_affinity_w:
                return {}
            names = [n.name for n in ssn.node_list]
            index = interpod.get_index(ssn, names)
            raw = index.preference_score(task)
            if raw is None:
                return {}
            norm = interpod.normalize(raw, float(self.pod_affinity_w))
            by_name = dict(zip(names, norm))
            return {node.name: float(by_name.get(node.name, 0.0))
                    for node in nodes}

        ssn.add_batch_node_order_fn(NAME, batch_node_order_fn)

    def _static_score(self, ssn):
        from . import interpod

        def fn(batch, narr, feats):
            # the [G, N] score materializes ONLY on first touch: the
            # all-pass case previously paid a ~256 MB zeros alloc per
            # context build at 50k x 10k before returning None
            score = None
            touched = False   # all-zero -> return None (no [G,N] transfer)
            n = len(narr.names)

            def buf():
                nonlocal score
                if score is None:
                    score = np.zeros((batch.g_pad, narr.n_pad), np.float32)
                return score
            if self.pod_affinity_w:
                # inter-pod preferred (anti-)affinity batch scorer
                # (nodeorder.go:271-295); symmetry can score affinity-free
                # groups, so gate on any affinity existing at all
                own = {g for g, i in enumerate(batch.group_first)
                       if interpod.task_has_pod_affinity(batch.tasks[i])}
                existing = any(interpod.task_has_pod_affinity(t)
                               for node in ssn.nodes.values()
                               for t in node.tasks.values())
                if own or existing:
                    index = interpod.get_index(ssn, narr.names)
                    groups = set(range(batch.n_groups)) \
                        if index.pref_terms else own
                    for g in groups:
                        rep = batch.tasks[batch.group_first[g]]
                        raw = index.preference_score(rep)
                        if raw is not None:
                            buf()[g, :n] += interpod.normalize(
                                raw, float(self.pod_affinity_w))
                            touched = True
            # PreferNoSchedule taints are rare: sweep only nodes that carry
            # one (taint-free nodes score a constant, which can't change the
            # per-task argmax and is omitted)
            taint_nodes = [
                (name, i) for name, i in narr.name_to_idx.items()
                if ssn.nodes[name].node is not None
                and any(t.effect == "PreferNoSchedule"
                        for t in ssn.nodes[name].node.spec.taints)]
            for g, ti in enumerate(batch.group_first):
                rep = batch.tasks[ti]
                has_pref = (rep.pod.spec.affinity is not None
                            and rep.pod.spec.affinity.node_affinity is not None
                            and rep.pod.spec.affinity.node_affinity.preferred)
                if has_pref and self.node_affinity_w:
                    for name, i in narr.name_to_idx.items():
                        labels = ssn.nodes[name].node.metadata.labels \
                            if ssn.nodes[name].node else {}
                        buf()[g, i] += self.node_affinity_w * \
                            _preferred_affinity_score(rep, labels)
                    touched = True
                if self.taint_w and taint_nodes:
                    touched = True
                    for name, i in taint_nodes:
                        # relative to the taint-free constant of 100
                        buf()[g, i] += self.taint_w * (
                            _prefer_no_schedule_score(rep, ssn.nodes[name]) - 100.0)
            return score if touched else None
        return fn


register_plugin_builder(NAME, NodeOrderPlugin)
