"""CPU-manager hint provider: replicates the kubelet static CPU manager's
topology-aware allocation
(reference: pkg/scheduler/plugins/numaaware/provider/cpumanager/
{cpu_mng,cpu_assignment}.go).
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, List, Optional, Sequence, Set

from ...models.resource import CPU, milli_value
from .policy import TopologyHint, mask_bits, mask_count, mask_of


class CPUDetails:
    """Topology lookups over {cpu_id: CpuInfo} (kubelet topology.CPUDetails)."""

    def __init__(self, detail: Dict[int, object]):
        self.detail = detail

    def cpus(self) -> Set[int]:
        return set(self.detail.keys())

    def sockets(self) -> List[int]:
        return sorted({c.socket_id for c in self.detail.values()})

    def cores(self) -> List[tuple]:
        return sorted({(c.socket_id, c.core_id) for c in self.detail.values()})

    def numa_nodes(self) -> List[int]:
        return sorted({c.numa_id for c in self.detail.values()})

    def cpus_in_socket(self, socket_id: int) -> Set[int]:
        return {i for i, c in self.detail.items() if c.socket_id == socket_id}

    def cpus_in_core(self, socket_id: int, core_id: int) -> Set[int]:
        return {i for i, c in self.detail.items()
                if c.socket_id == socket_id and c.core_id == core_id}

    def cpus_in_numa_nodes(self, numa_ids: Sequence[int]) -> Set[int]:
        ids = set(numa_ids)
        return {i for i, c in self.detail.items() if c.numa_id in ids}

    def numa_of(self, cpu_id: int) -> int:
        return self.detail[cpu_id].numa_id


def take_by_topology(details: CPUDetails, available: Set[int],
                     count: int) -> Set[int]:
    """cpu_assignment.go takeByTopology: whole sockets, then whole cores,
    then single CPUs packing partially-used cores first.

    Raises ValueError when not enough CPUs are available."""
    if count > len(available):
        raise ValueError(
            f"not enough cpus available to satisfy request: want {count}, "
            f"have {len(available)}")
    if count <= 0:
        return set()
    taken: Set[int] = set()
    remaining = count

    # 1. whole sockets that are fully free and fit
    for socket_id in details.sockets():
        cpus = details.cpus_in_socket(socket_id)
        if cpus and cpus <= available - taken and len(cpus) <= remaining:
            taken |= cpus
            remaining -= len(cpus)
            if remaining == 0:
                return taken

    # 2. whole cores that are fully free and fit
    for socket_id, core_id in details.cores():
        cpus = details.cpus_in_core(socket_id, core_id)
        free = cpus & (available - taken)
        if free == cpus and cpus and len(cpus) <= remaining:
            taken |= cpus
            remaining -= len(cpus)
            if remaining == 0:
                return taken

    # 3. single CPUs: prefer cores with the fewest free CPUs (pack partial
    # cores), then lowest id — the kubelet's free-CPU sort order
    free_left = sorted(
        available - taken,
        key=lambda i: (len(details.cpus_in_core(
            details.detail[i].socket_id, details.detail[i].core_id)
            & (available - taken)), i))
    taken |= set(free_left[:remaining])
    return taken


def guaranteed_cpus(container) -> int:
    """cpu_mng.go:46-53 — integral CPU request, else 0 (no exclusive set)."""
    if CPU not in container.requests:
        return 0
    milli = milli_value(container.requests[CPU])
    if milli <= 0 or milli % 1000 != 0:
        return 0
    return int(milli // 1000)


def generate_cpu_topology_hints(available: Set[int], details: CPUDetails,
                                request: int) -> List[TopologyHint]:
    """cpu_mng.go:57-104 — one hint per NUMA mask that can satisfy the
    request from available CPUs; preferred iff the mask is minimal in size
    among masks whose total capacity fits the request."""
    numa_nodes = details.numa_nodes()
    min_affinity_size = len(numa_nodes)
    hints: List[TopologyHint] = []
    for size in range(1, len(numa_nodes) + 1):
        for combo in itertools.combinations(numa_nodes, size):
            mask = mask_of(combo)
            in_mask = details.cpus_in_numa_nodes(combo)
            if len(in_mask) >= request and size < min_affinity_size:
                min_affinity_size = size
            if len(available & in_mask) < request:
                continue
            hints.append(TopologyHint(mask, False))
    return [TopologyHint(h.affinity,
                         mask_count(h.affinity) == min_affinity_size)
            for h in hints]


class CpuManager:
    """The cpuMng hint provider (cpu_mng.go)."""

    def name(self) -> str:
        return "cpuMng"

    def _reserved(self, details: CPUDetails, topo_info) -> Set[int]:
        reserved_milli = topo_info.res_reserved.get(CPU, 0)
        if not reserved_milli:
            return set()
        num_reserved = int(math.ceil(float(reserved_milli) / 1000.0))
        try:
            return take_by_topology(details, details.cpus(), num_reserved)
        except ValueError:
            return set()

    def get_topology_hints(self, container, topo_info,
                           res_numa_sets) -> Optional[Dict[str, List[TopologyHint]]]:
        """cpu_mng.go:106-147"""
        request = guaranteed_cpus(container)
        if request == 0:
            return None
        details = CPUDetails(topo_info.cpu_detail)
        available = set(res_numa_sets.get(CPU, set()))
        available -= self._reserved(details, topo_info)
        return {CPU: generate_cpu_topology_hints(available, details, request)}

    def allocate(self, container, best_hint, topo_info,
                 res_numa_sets) -> Dict[str, Set[int]]:
        """cpu_mng.go:149-210 — aligned CPUs from the hint's NUMA nodes
        first, topping up from the remainder."""
        request = guaranteed_cpus(container)
        if request == 0:
            return {}
        details = CPUDetails(topo_info.cpu_detail)
        available = set(res_numa_sets.get(CPU, set()))
        available -= self._reserved(details, topo_info)

        result: Set[int] = set()
        if best_hint.affinity is not None:
            aligned = available & details.cpus_in_numa_nodes(
                mask_bits(best_hint.affinity))
            num_aligned = min(request, len(aligned))
            try:
                result |= take_by_topology(details, aligned, num_aligned)
            except ValueError:
                return {CPU: set()}
        try:
            result |= take_by_topology(details, available - result,
                                       request - len(result))
        except ValueError:
            return {CPU: set()}
        return {CPU: result}
