"""numaaware plugin (reference: pkg/scheduler/plugins/numaaware/
numaaware.go): topology-manager-style NUMA admission and scoring.

Extension points: Predicate (per-task policy admission + tentative CPU-set
assignment), BatchNodeOrder (fewer NUMA nodes spanned scores higher),
EventHandler (allocate/release assigned sets against the session view), and
OnSessionClose (push allocated sets back through the cache,
UpdateSchedulerNumaInfo).

Host-side by design: NUMA admission runs only for Guaranteed pods with a
topology policy — a rare, deeply branchy per-node decision (hint powersets
over <=8 NUMA nodes) that would not tile onto the MXU; the dense task x node
resource fit stays in the vmapped solver kernels (ops/fit.py).
"""

from __future__ import annotations

from typing import Dict, List, Set

from ...framework.plugin import Plugin
from ...framework.registry import register_plugin_builder
from ...models.resource import CPU, milli_value
from . import policy as numa_policy
from .cpumanager import CPUDetails, CpuManager
from .policy import (CPU_MANAGER_POLICY, POLICY_NONE,
                     TOPOLOGY_MANAGER_POLICY, accumulate_providers_hints,
                     get_policy, mask_bits)

NAME = "numa-aware"
WEIGHT_ARG = "weight"


def is_guaranteed(pod) -> bool:
    """k8s Guaranteed QoS: every container's requests == limits with both
    cpu and memory set (v1qos.GetPodQOS, numaaware.go:117)."""
    containers = pod.spec.containers + pod.spec.init_containers
    if not containers:
        return False
    for c in containers:
        if not c.requests or not c.limits:
            return False
        if CPU not in c.requests or "memory" not in c.requests:
            return False
        for res, req in c.requests.items():
            lim = c.limits.get(res)
            if lim is None or milli_value(lim) != milli_value(req):
                return False
    return True


def generate_numa_nodes(nodes) -> Dict[str, List[int]]:
    """api.GenerateNumaNodes — NUMA node ids per node."""
    out = {}
    for name, node in nodes.items():
        if node.numa_scheduler_info is not None:
            out[name] = CPUDetails(
                node.numa_scheduler_info.cpu_detail).numa_nodes()
    return out


def generate_node_res_numa_sets(nodes) -> Dict[str, Dict[str, Set[int]]]:
    """api.GenerateNodeResNumaSets — allocatable id-sets per node/resource."""
    out = {}
    for name, node in nodes.items():
        if node.numa_scheduler_info is None:
            continue
        out[name] = {res: set(ri.allocatable)
                     for res, ri in node.numa_scheduler_info.numa_res_map.items()}
    return out


class NumaAwarePlugin(Plugin):
    def __init__(self, arguments=None):
        args = arguments or {}
        if hasattr(args, "get_int"):
            self.weight = args.get_int(WEIGHT_ARG, 1)
        else:
            self.weight = int(args.get(WEIGHT_ARG, 1))
        self.hint_providers = [CpuManager()]
        # taskUID -> {node name -> {res -> set of ids}} (numaaware.go:52-55)
        self.assign_res: Dict[str, Dict[str, Dict[str, Set[int]]]] = {}
        self.node_res_sets: Dict[str, Dict[str, Set[int]]] = {}
        self.task_bind_node: Dict[str, str] = {}

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        numa_nodes = generate_numa_nodes(ssn.nodes)
        self.node_res_sets = generate_node_res_numa_sets(ssn.nodes)

        from ...framework.session import EventHandler

        def on_allocate(event) -> None:
            """numaaware.go:86-100. The batch solver evaluates host
            predicates once per task group, so a non-representative task may
            arrive here without a tentative assignment — compute it now
            against the current NUMA view (feasibility was already checked
            group-wide; this keeps per-task CPU sets exact)."""
            task = event.task
            per_node = self.assign_res.get(task.uid)
            sets = per_node.get(task.node_name) if per_node else None
            if sets is None:
                node = ssn.nodes.get(task.node_name)
                if node is None:
                    return
                try:
                    sets = self._compute_assign(task, node, numa_nodes)
                except ValueError:
                    sets = None
                if sets is None:
                    return
                self.assign_res.setdefault(task.uid, {})[task.node_name] = sets
            node_sets = self.node_res_sets.get(task.node_name)
            if node_sets is not None:
                for res, taken in sets.items():
                    node_sets.setdefault(res, set()).difference_update(taken)
            self.task_bind_node[task.uid] = task.node_name

        def on_deallocate(event) -> None:
            """numaaware.go:101-114"""
            task = event.task
            per_node = self.assign_res.get(task.uid)
            if per_node is None:
                return
            sets = per_node.get(task.node_name)
            if sets is None:
                return
            self.task_bind_node.pop(task.uid, None)
            node_sets = self.node_res_sets.get(task.node_name)
            if node_sets is not None:
                for res, returned in sets.items():
                    node_sets.setdefault(res, set()).update(returned)

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate))

        def predicate_fn(task, node) -> None:
            """numaaware.go:116-157 — policy admission + tentative assign."""
            sets = self._compute_assign(task, node, numa_nodes)
            if sets is not None:
                self.assign_res.setdefault(task.uid, {})[node.name] = sets

        ssn.add_predicate_fn(NAME, predicate_fn)

        def batch_node_order_fn(task, node_infos) -> Dict[str, float]:
            """numaaware.go:160-183 — fewer NUMA nodes spanned is better."""
            scores: Dict[str, float] = {}
            if task.topology_policy in ("", POLICY_NONE):
                return scores
            per_node = self.assign_res.get(task.uid)
            if not per_node:
                return scores
            numa_counts: Dict[str, int] = {}
            for node in node_infos:
                sets = per_node.get(node.name)
                if sets is None or node.numa_scheduler_info is None:
                    continue
                details = CPUDetails(node.numa_scheduler_info.cpu_detail)
                spanned = {details.numa_of(c) for c in sets.get(CPU, set())
                           if c in details.detail}
                numa_counts[node.name] = len(spanned)
            if not numa_counts:
                return scores
            # NormalizeScore(100, reverse=True): fewest NUMA nodes -> 100
            max_count = max(numa_counts.values()) or 1
            for name, count in numa_counts.items():
                scores[name] = (100.0 * (max_count - count) / max_count) \
                    * self.weight
            return scores

        ssn.add_batch_node_order_fn(NAME, batch_node_order_fn)

    def _compute_assign(self, task, node, numa_nodes):
        """Policy admission + per-container CPU-set assignment
        (numaaware.go:116-157). Returns {res: set} or None when the task is
        out of scope; raises ValueError when the node must be rejected."""
        if not is_guaranteed(task.pod):
            return None
        fit, reason = self._filter_node_by_policy(task, node)
        if not fit:
            if reason:
                raise ValueError(reason)
            return None
        res_numa_sets = {res: set(ids) for res, ids in
                         self.node_res_sets.get(node.name, {}).items()}
        task_policy = get_policy(node, numa_nodes.get(node.name, []))
        all_assign: Dict[str, Set[int]] = {}
        for container in task.pod.spec.containers:
            providers_hints = accumulate_providers_hints(
                container, node.numa_scheduler_info, res_numa_sets,
                self.hint_providers)
            best_hint, admit = task_policy.predicate(providers_hints)
            if not admit:
                raise ValueError(
                    f"plugin {NAME} predicates failed for task {task.name} "
                    f"container {container.name} on node {node.name}")
            assign = numa_policy.allocate(
                container, best_hint, node.numa_scheduler_info,
                res_numa_sets, self.hint_providers)
            for res, ids in assign.items():
                all_assign.setdefault(res, set()).update(ids)
                res_numa_sets.setdefault(res, set()).difference_update(ids)
        return all_assign

    def _filter_node_by_policy(self, task, node):
        """numaaware.go:186-225 -> (fit, error_reason|None)"""
        info = node.numa_scheduler_info
        if task.topology_policy not in ("", POLICY_NONE):
            if info is None:
                return False, "numa info is empty"
            if info.policies.get(CPU_MANAGER_POLICY) != "static":
                return False, "cpu manager policy isn't static"
            if task.topology_policy != info.policies.get(TOPOLOGY_MANAGER_POLICY):
                return False, (
                    f"task topology policy[{task.topology_policy}] is "
                    f"different with node"
                    f"[{info.policies.get(TOPOLOGY_MANAGER_POLICY)}]")
            if node.name not in self.node_res_sets:
                return False, "no topo information"
            if not self.node_res_sets[node.name].get(CPU):
                return False, "cpu allocatable map is empty"
            return True, None
        # tasks without a policy: NUMA-manage them only on static+managed
        # nodes, silently skip elsewhere
        if info is None:
            return False, None
        if info.policies.get(CPU_MANAGER_POLICY) != "static":
            return False, None
        if info.policies.get(TOPOLOGY_MANAGER_POLICY, "") in ("", POLICY_NONE):
            return False, None
        return True, None

    def on_session_close(self, ssn) -> None:
        """numaaware.go:251-279 — aggregate bound assignments, push to cache."""
        if not self.task_bind_node:
            return
        allocated: Dict[str, Dict[str, Set[int]]] = {}
        for task_uid, node_name in self.task_bind_node.items():
            sets = self.assign_res.get(task_uid, {}).get(node_name)
            if sets is None:
                continue
            node_alloc = allocated.setdefault(node_name, {})
            for res, ids in sets.items():
                node_alloc.setdefault(res, set()).update(ids)
        ssn.cache.update_scheduler_numa_info(allocated)


register_plugin_builder(NAME, NumaAwarePlugin)
