"""Topology-manager policies for the numaaware plugin
(reference: pkg/scheduler/plugins/numaaware/policy/{policy,factory,
policy_none,policy_best_effort,policy_restricted,policy_single_numa_node}.go).

NUMA affinities are integer bitmasks (bit i = NUMA node i). A TopologyHint
is (affinity mask | None, preferred); merging takes the bitwise-AND over one
hint per provider-resource and keeps the narrowest preferred result.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

CPU_MANAGER_POLICY = "CPUManagerPolicy"        # nodeinfo CRD policy keys
TOPOLOGY_MANAGER_POLICY = "TopologyManagerPolicy"

POLICY_NONE = "none"
POLICY_BEST_EFFORT = "best-effort"
POLICY_RESTRICTED = "restricted"
POLICY_SINGLE_NUMA_NODE = "single-numa-node"


def mask_of(bits: Sequence[int]) -> int:
    mask = 0
    for b in bits:
        mask |= 1 << b
    return mask


def mask_bits(mask: int) -> List[int]:
    out, i = [], 0
    while mask >> i:
        if (mask >> i) & 1:
            out.append(i)
        i += 1
    return out


def mask_count(mask: int) -> int:
    return bin(mask).count("1")


def is_narrower(a: int, b: int) -> bool:
    """kubelet bitmask.IsNarrowerThan: fewer bits wins; ties by lower value."""
    ca, cb = mask_count(a), mask_count(b)
    if ca != cb:
        return ca < cb
    return a < b


@dataclass(frozen=True)
class TopologyHint:
    """policy.go:28-35 — affinity None means 'any NUMA placement'."""
    affinity: Optional[int]
    preferred: bool


def filter_providers_hints(
        providers_hints: List[Dict[str, List[TopologyHint]]]
) -> List[List[TopologyHint]]:
    """policy.go:24-52 — one hint list per provider-resource; providers with
    no opinion contribute a single preferred any-NUMA hint, providers with an
    empty list contribute a non-preferred any-NUMA hint."""
    all_hints: List[List[TopologyHint]] = []
    for hints in providers_hints:
        if not hints:
            all_hints.append([TopologyHint(None, True)])
            continue
        for resource, res_hints in hints.items():
            if res_hints is None:
                all_hints.append([TopologyHint(None, True)])
            elif len(res_hints) == 0:
                all_hints.append([TopologyHint(None, False)])
            else:
                all_hints.append(res_hints)
    return all_hints


def merge_permutation(default_affinity: int,
                      permutation: Sequence[TopologyHint]) -> TopologyHint:
    """policy.go:141-166 — AND of affinities; preferred iff all preferred."""
    preferred = True
    merged = default_affinity
    for hint in permutation:
        merged &= default_affinity if hint.affinity is None else hint.affinity
        if not hint.preferred:
            preferred = False
    return TopologyHint(merged, preferred)


def merge_filtered_hints(numa_nodes: Sequence[int],
                         filtered: List[List[TopologyHint]]) -> TopologyHint:
    """policy.go:54-100 — best merged hint over the hint cross-product."""
    default_affinity = mask_of(numa_nodes)
    best = TopologyHint(default_affinity, False)
    for permutation in itertools.product(*filtered):
        merged = merge_permutation(default_affinity, permutation)
        if mask_count(merged.affinity) == 0:
            continue
        if merged.preferred and not best.preferred:
            best = merged
            continue
        if not merged.preferred and best.preferred:
            continue
        if not is_narrower(merged.affinity, best.affinity):
            continue
        best = merged
    return best


class Policy:
    def predicate(self, providers_hints) -> tuple:
        """-> (best_hint, admit)"""
        raise NotImplementedError


class PolicyNone(Policy):
    """policy_none.go — everything admitted, no affinity."""

    def __init__(self, numa_nodes: Sequence[int] = ()):
        self.numa_nodes = list(numa_nodes)

    def predicate(self, providers_hints):
        return TopologyHint(None, True), True


class PolicyBestEffort(Policy):
    """policy_best_effort.go — merge, always admit."""

    def __init__(self, numa_nodes: Sequence[int]):
        self.numa_nodes = list(numa_nodes)

    def predicate(self, providers_hints):
        filtered = filter_providers_hints(providers_hints)
        best = merge_filtered_hints(self.numa_nodes, filtered)
        return best, True


class PolicyRestricted(Policy):
    """policy_restricted.go — admit only preferred placements."""

    def __init__(self, numa_nodes: Sequence[int]):
        self.numa_nodes = list(numa_nodes)

    def predicate(self, providers_hints):
        filtered = filter_providers_hints(providers_hints)
        best = merge_filtered_hints(self.numa_nodes, filtered)
        return best, best.preferred


class PolicySingleNumaNode(Policy):
    """policy_single_numa_node.go — only single-node preferred hints."""

    def __init__(self, numa_nodes: Sequence[int]):
        self.numa_nodes = list(numa_nodes)

    @staticmethod
    def _filter_single_numa(filtered: List[List[TopologyHint]]):
        out = []
        for res_hints in filtered:
            kept = [h for h in res_hints
                    if h.preferred and
                    (h.affinity is None or mask_count(h.affinity) == 1)]
            out.append(kept)
        return out

    def predicate(self, providers_hints):
        filtered = filter_providers_hints(providers_hints)
        single = self._filter_single_numa(filtered)
        best = merge_filtered_hints(self.numa_nodes, single)
        return best, best.preferred


def get_policy(node, numa_nodes: Sequence[int]) -> Policy:
    """factory.go:54-68 — policy from the node's topology-manager policy."""
    name = ""
    if node.numa_scheduler_info is not None:
        name = node.numa_scheduler_info.policies.get(TOPOLOGY_MANAGER_POLICY, "")
    return {
        POLICY_NONE: PolicyNone,
        POLICY_BEST_EFFORT: PolicyBestEffort,
        POLICY_RESTRICTED: PolicyRestricted,
        POLICY_SINGLE_NUMA_NODE: PolicySingleNumaNode,
    }.get(name, PolicyNone)(numa_nodes)


def accumulate_providers_hints(container, topo_info, res_numa_sets,
                               hint_providers):
    """factory.go:70-80"""
    return [p.get_topology_hints(container, topo_info, res_numa_sets)
            for p in hint_providers]


def allocate(container, best_hint, topo_info, res_numa_sets, hint_providers):
    """factory.go:82-94 — union of every provider's assignment."""
    all_alloc: Dict[str, set] = {}
    for provider in hint_providers:
        for res, assign in provider.allocate(
                container, best_hint, topo_info, res_numa_sets).items():
            all_alloc[res] = assign
    return all_alloc
