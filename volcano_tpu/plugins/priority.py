"""priority plugin (reference: pkg/scheduler/plugins/priority/priority.go).

TaskOrder/JobOrder by priority; Preemptable admits only strictly
lower-priority victims. With ``tieredpack.weight`` set, the plugin also
contributes the priority-tiered packing score (arxiv 2511.08373,
lowered by ops/constraints.py): groups pack toward nodes resident to
their own-or-higher priority tier and away from lower-tier nodes, so
high-priority work lands where future preemption fallout is smallest.
"""

from __future__ import annotations

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT

NAME = "priority"


class PriorityPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        args = self.arguments
        get_f = args.get_float if hasattr(args, "get_float") else \
            (lambda k, d: float(args.get(k, d) or d))
        self.tieredpack_w = get_f("tieredpack.weight", 0.0)

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        if self.tieredpack_w and ssn.solver is not None:
            from ..ops import constraints
            # the explain layer's score-term decomposition re-derives
            # the tieredpack term for top-k candidates and needs the
            # session's configured weight (trace/explain.py)
            ssn._tieredpack_weight = self.tieredpack_w

            def tiered_score(batch, narr, feats):
                return constraints.score_or_fallback(
                    ssn, batch, narr, tiered_weight=self.tieredpack_w,
                    spread_weight=0.0)   # spread rides the predicates plugin
            ssn.solver.add_static_score_fn(tiered_score)

        def task_order_fn(l, r):
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        # marker: this comparator is EXACTLY the dispatch fallback's
        # (priority desc) — hot callers key-sort instead of running a
        # cmp dispatch per comparison (actions/allocate._pending_tasks)
        task_order_fn.standard_priority_order = True
        ssn.add_task_order_fn(NAME, task_order_fn)

        def job_order_fn(l, r):
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_job_order_fn(NAME, job_order_fn)

        def preemptable_fn(preemptor, preemptees):
            """Only strictly lower priority tasks are victims
            (priority.go:79-108)."""
            preemptor_job = ssn.jobs.get(preemptor.job)
            if preemptor_job is None:
                return [], PERMIT
            victims = [t for t in preemptees
                       if ssn.jobs.get(t.job) is not None
                       and ssn.jobs[t.job].priority < preemptor_job.priority]
            return victims, PERMIT

        ssn.add_preemptable_fn(NAME, preemptable_fn)


register_plugin_builder(NAME, PriorityPlugin)
