"""predicates plugin (reference: pkg/scheduler/plugins/predicates/
predicates.go).

Wraps the standard node filters: NodeUnschedulable (handled by the cache --
NotReady nodes never reach the snapshot), node selector / required node
affinity, taints/tolerations, pod-count cap, host ports, and GPU-share fit.

TPU-first: for the batch solver these predicates are *vectorized* -- the
plugin flips on the solver's feature-matrix kernels (selector/taint/affinity
matmuls built at snapshot time, models/arrays.py PredicateFeatures) and adds
mask fns for ports and GPU sharing. The same checks are also registered as a
host-side PredicateFn for actions that probe single task x node pairs
(preempt/reclaim/backfill), keeping both paths semantically identical.
"""

from __future__ import annotations

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..models.resource import GPU_MEMORY_RESOURCE
from ..models.unschedule_info import (FitError, NODE_AFFINITY_FAILED,
                                      NODE_POD_NUMBER_EXCEEDED,
                                      NODE_PORT_FAILED, NODE_SELECTOR_FAILED,
                                      TAINT_FAILED)

POD_AFFINITY_FAILED = "node(s) didn't match pod affinity/anti-affinity"
POD_TEMPLATE_KEY = "volcano.sh/template-uid"   # batch/v1alpha1/labels.go:37


class PredicateCache:
    """Per-(node, pod-template-uid) fit memo (predicates/cache.go): pods
    stamped with the same template annotation share one predicate verdict
    per node. The vectorized solver path gets the same effect from task
    grouping; this serves the host predicate path when
    ``predicate.CacheEnable`` is set."""

    def __init__(self):
        self._cache = {}   # node -> {template_uid: fit}

    @staticmethod
    def template_uid(pod) -> str:
        return pod.metadata.annotations.get(POD_TEMPLATE_KEY, "")

    def get(self, node_name: str, pod):
        uid = self.template_uid(pod)
        if not uid:
            return None
        return self._cache.get(node_name, {}).get(uid)

    def update(self, node_name: str, pod, fit: bool) -> None:
        uid = self.template_uid(pod)
        if uid:
            self._cache.setdefault(node_name, {})[uid] = fit


def _parse_proportional(args) -> dict:
    """predicate.resources.<name>.{cpu,memory} rates
    (predicates.go:124-151)."""
    get_str = args.get_str if hasattr(args, "get_str") else \
        (lambda k, d="": str(args.get(k, d) or d))
    get_f = args.get_float if hasattr(args, "get_float") else \
        (lambda k, d: float(args.get(k, d) or d))
    out = {}
    for res in get_str("predicate.resources", "").split(","):
        res = res.strip()
        if not res:
            continue
        cpu = get_f(f"predicate.resources.{res}.cpu", 1.0)
        mem = get_f(f"predicate.resources.{res}.memory", 1.0)
        out[res] = (cpu if cpu >= 0 else 1.0, mem if mem >= 0 else 1.0)
    return out


def _proportional_ok(task, node, proportional: dict) -> bool:
    """Reserve cpu/memory in proportion to a node's idle special resource
    (predicates/proportional.go): tasks NOT requesting the resource must
    leave idle_cpu >= idle_res * rate_cpu and likewise for memory."""
    for res in proportional:
        if task.resreq.get(res) > 0:
            return True   # requesters are exempt
    for res, (cpu_rate, mem_rate) in proportional.items():
        idle_res = node.idle.get(res)
        if idle_res <= 0:
            continue
        cpu_reserved = idle_res * cpu_rate
        mem_reserved = idle_res * mem_rate * 1000 * 1000
        if node.idle.milli_cpu - task.resreq.milli_cpu < cpu_reserved or \
                node.idle.memory - task.resreq.memory < mem_reserved:
            return False
    return True

NAME = "predicates"


class FitException(Exception):
    def __init__(self, fit_error: FitError):
        super().__init__(fit_error.error())
        self.fit_error = fit_error


def _node_selector_ok(task, node) -> bool:
    labels = node.node.metadata.labels if node.node is not None else {}
    for k, v in task.pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    return True


def _node_affinity_ok(task, node) -> bool:
    aff = task.pod.spec.affinity
    if aff is None or aff.node_affinity is None or not aff.node_affinity.required:
        return True
    labels = node.node.metadata.labels if node.node is not None else {}
    return any(term.matches(labels) for term in aff.node_affinity.required)


def _taints_ok(task, node) -> bool:
    if node.node is None:
        return True
    for taint in node.node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(tol.tolerates(taint) for tol in task.pod.spec.tolerations):
            return False
    return True


def _ports_ok(task, node) -> bool:
    want = set(task.pod.spec.host_ports)
    if not want:
        return True
    used = set()
    for t in node.tasks.values():
        used.update(t.pod.spec.host_ports)
    return not (want & used)


def _gpu_share_ok(task, node) -> bool:
    """GPU-share fit: some card must have enough free gpu-memory
    (predicates.go:343-352 + gpu.go checkNodeGPUSharingPredicate)."""
    mem = task.resreq.get(GPU_MEMORY_RESOURCE) / 1000.0
    if mem <= 0:
        return True
    idle = node.get_devices_idle_gpu_memory()
    return any(free >= mem for free in idle.values())


class PredicatesPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        args = self.arguments
        get_bool = args.get_bool if hasattr(args, "get_bool") else \
            (lambda k, d=False: str(args.get(k, d)).lower() in
             ("true", "1", "yes"))
        self.cache_enable = get_bool("predicate.CacheEnable", False)
        self.proportional = _parse_proportional(args) \
            if get_bool("predicate.ProportionalEnable", False) else {}
        self._pcache = PredicateCache()

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        from . import interpod

        # vectorized path: selector/taints/affinity matrices + extra masks
        if ssn.solver is not None and ssn.plugin_enabled(NAME, "enabledPredicate"):
            ssn.solver.enable_default_predicates = True
            ssn.solver.mark_vectorized(NAME)
            ssn.solver.add_mask_fn(self._ports_and_gpu_mask(ssn))
            ssn.solver.add_mask_fn(self._constraint_mask(ssn))
            ssn.solver.add_static_score_fn(self._constraint_score(ssn))
            if self.proportional:
                ssn.solver.add_mask_fn(self._proportional_mask())

        def stable_predicates(task, node):
            """Selector/affinity/taints — the template-cacheable filters
            (predicateByStablefilter, predicates.go:280-301)."""
            if not _node_selector_ok(task, node):
                return NODE_SELECTOR_FAILED
            if not _node_affinity_ok(task, node):
                return NODE_AFFINITY_FAILED
            if not _taints_ok(task, node):
                return TAINT_FAILED
            return None

        def predicate_fn(task, node):
            """Host path for single-pair probes."""
            cap = node.allocatable.max_task_num
            if cap and len(node.tasks) >= cap:
                raise FitException(FitError(task=task, node=node,
                                            reasons=[NODE_POD_NUMBER_EXCEEDED]))
            if self.cache_enable and PredicateCache.template_uid(task.pod):
                fit = self._pcache.get(node.name, task.pod)
                if fit is None:
                    reason = stable_predicates(task, node)
                    self._pcache.update(node.name, task.pod, reason is None)
                    if reason is not None:
                        raise FitException(FitError(task=task, node=node,
                                                    reasons=[reason]))
                elif not fit:
                    raise FitException(FitError(
                        task=task, node=node,
                        reasons=["equivalence cache predicates failed"]))
            else:
                reason = stable_predicates(task, node)
                if reason is not None:
                    raise FitException(FitError(task=task, node=node,
                                                reasons=[reason]))
            if not _ports_ok(task, node):
                raise FitException(FitError(task=task, node=node,
                                            reasons=[NODE_PORT_FAILED]))
            if not _gpu_share_ok(task, node):
                raise FitException(FitError(
                    task=task, node=node,
                    reasons=["node(s) didn't have enough free gpu memory"]))
            # InterPodAffinity filter (predicates.go:334-341)
            names = [n.name for n in ssn.node_list]
            index = interpod.get_index(ssn, names)
            if index.anti_required or interpod.task_has_pod_affinity(task):
                mask = index.required_mask(task)
                if mask is not None:
                    try:
                        i = names.index(node.name)
                    except ValueError:
                        i = -1
                    if i >= 0 and not mask[i]:
                        raise FitException(FitError(
                            task=task, node=node,
                            reasons=[POD_AFFINITY_FAILED]))
            # topology-spread / self-anti slot assignment (the per-pair
            # reference of the compiled constraint mask — identical
            # semantics by construction, parity-pinned)
            from ..ops import constraints
            if not constraints.node_satisfies_slots(ssn, task, node):
                raise FitException(FitError(
                    task=task, node=node,
                    reasons=["node(s) didn't satisfy topology spread "
                             "constraints"]))
            # proportional resource reserve (predicates.go:353-361)
            if self.proportional and \
                    not _proportional_ok(task, node, self.proportional):
                raise FitException(FitError(
                    task=task, node=node,
                    reasons=["proportional resource reserve check failed"]))

        ssn.add_predicate_fn(NAME, predicate_fn)

    def _proportional_mask(self):
        def mask_fn(batch, narr, feats):
            """Vectorized proportional reserve: for groups NOT requesting a
            proportional resource, nodes must keep idle cpu/mem above
            idle_res x rate after placement (proportional.go)."""
            mask = None   # None = pass-through (no dense [G,N] transfer)
            rindex = narr.rindex
            for res, (cpu_rate, mem_rate) in self.proportional.items():
                ri = rindex.index.get(res)
                if ri is None:
                    continue
                if mask is None:
                    mask = np.ones((batch.g_pad, narr.n_pad), bool)
                idle_res = narr.idle[:, ri] / rindex.scales[ri]   # raw units
                applies_node = idle_res > 0                        # [N]
                cpu_reserved = idle_res * cpu_rate                 # millicores
                mem_reserved = idle_res * mem_rate * 1e6 * \
                    rindex.scales[1]                               # scaled mem
                for g, ti in enumerate(batch.group_first):
                    rep = batch.tasks[ti]
                    if rep.resreq.get(res) > 0:
                        continue   # requesters are exempt
                    left_cpu = narr.idle[:, 0] - batch.group_req[g, 0]
                    left_mem = narr.idle[:, 1] - batch.group_req[g, 1]
                    ok = ~applies_node | ((left_cpu >= cpu_reserved)
                                          & (left_mem >= mem_reserved))
                    mask[g] &= ok
            return mask
        mask_fn.explain_label = "proportional"
        return mask_fn

    def _constraint_mask(self, ssn):
        """The compiled constraint MASK (ops/constraints.py): interpod
        required (anti-)affinity + the topology-spread / self-anti slot
        rows, with the per-task Python reference as the crash fallback."""
        from ..ops import constraints

        def mask_fn(batch, narr, feats):
            return constraints.masked_or_reference(ssn, batch, narr)
        # interpod required (anti-)affinity + dense spread slot rows:
        # the explain ladder's "affinity" stage
        mask_fn.explain_label = "affinity"
        return mask_fn

    def _constraint_score(self, ssn):
        """The compiled constraint SCORE: soft (ScheduleAnyway) topology
        spread; priority-tiered packing rides the priority plugin."""
        from ..ops import constraints

        def score_fn(batch, narr, feats):
            return constraints.score_or_fallback(ssn, batch, narr)
        return score_fn

    def _ports_and_gpu_mask(self, ssn):
        def mask_fn(batch, narr, feats):
            mask = None   # None = pass-through (no dense [G,N] transfer)
            # only sweep groups that actually use host ports or shared GPUs
            for g, ti in enumerate(batch.group_first):
                rep = batch.tasks[ti]
                uses_ports = bool(rep.pod.spec.host_ports)
                uses_gpu = rep.resreq.get(GPU_MEMORY_RESOURCE) > 0
                if not (uses_ports or uses_gpu):
                    continue
                if mask is None:
                    mask = np.ones((batch.g_pad, narr.n_pad), bool)
                for name, i in narr.name_to_idx.items():
                    node = ssn.nodes[name]
                    if uses_ports and not _ports_ok(rep, node):
                        mask[g, i] = False
                    elif uses_gpu and not _gpu_share_ok(rep, node):
                        mask[g, i] = False
            return mask
        mask_fn.explain_label = "ports_gpu"
        return mask_fn


register_plugin_builder(NAME, PredicatesPlugin)
