"""predicates plugin (reference: pkg/scheduler/plugins/predicates/
predicates.go).

Wraps the standard node filters: NodeUnschedulable (handled by the cache --
NotReady nodes never reach the snapshot), node selector / required node
affinity, taints/tolerations, pod-count cap, host ports, and GPU-share fit.

TPU-first: for the batch solver these predicates are *vectorized* -- the
plugin flips on the solver's feature-matrix kernels (selector/taint/affinity
matmuls built at snapshot time, models/arrays.py PredicateFeatures) and adds
mask fns for ports and GPU sharing. The same checks are also registered as a
host-side PredicateFn for actions that probe single task x node pairs
(preempt/reclaim/backfill), keeping both paths semantically identical.
"""

from __future__ import annotations

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..models.node_info import get_gpu_memory_of_pod
from ..models.resource import GPU_MEMORY_RESOURCE, ZERO
from ..models.unschedule_info import (FitError, NODE_AFFINITY_FAILED,
                                      NODE_POD_NUMBER_EXCEEDED,
                                      NODE_PORT_FAILED, NODE_SELECTOR_FAILED,
                                      TAINT_FAILED)

NAME = "predicates"


class FitException(Exception):
    def __init__(self, fit_error: FitError):
        super().__init__(fit_error.error())
        self.fit_error = fit_error


def _node_selector_ok(task, node) -> bool:
    labels = node.node.metadata.labels if node.node is not None else {}
    for k, v in task.pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    return True


def _node_affinity_ok(task, node) -> bool:
    aff = task.pod.spec.affinity
    if aff is None or aff.node_affinity is None or not aff.node_affinity.required:
        return True
    labels = node.node.metadata.labels if node.node is not None else {}
    return any(term.matches(labels) for term in aff.node_affinity.required)


def _taints_ok(task, node) -> bool:
    if node.node is None:
        return True
    for taint in node.node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(tol.tolerates(taint) for tol in task.pod.spec.tolerations):
            return False
    return True


def _ports_ok(task, node) -> bool:
    want = set(task.pod.spec.host_ports)
    if not want:
        return True
    used = set()
    for t in node.tasks.values():
        used.update(t.pod.spec.host_ports)
    return not (want & used)


def _gpu_share_ok(task, node) -> bool:
    """GPU-share fit: some card must have enough free gpu-memory
    (predicates.go:343-352 + gpu.go checkNodeGPUSharingPredicate)."""
    mem = task.resreq.get(GPU_MEMORY_RESOURCE) / 1000.0
    if mem <= 0:
        return True
    idle = node.get_devices_idle_gpu_memory()
    return any(free >= mem for free in idle.values())


class PredicatesPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        # vectorized path: selector/taints/affinity matrices + extra masks
        if ssn.solver is not None and ssn.plugin_enabled(NAME, "enabledPredicate"):
            ssn.solver.enable_default_predicates = True
            ssn.solver.mark_vectorized(NAME)
            ssn.solver.add_mask_fn(self._ports_and_gpu_mask(ssn))

        def predicate_fn(task, node):
            """Host path for single-pair probes."""
            cap = node.allocatable.max_task_num
            if cap and len(node.tasks) >= cap:
                raise FitException(FitError(task=task, node=node,
                                            reasons=[NODE_POD_NUMBER_EXCEEDED]))
            if not _node_selector_ok(task, node):
                raise FitException(FitError(task=task, node=node,
                                            reasons=[NODE_SELECTOR_FAILED]))
            if not _node_affinity_ok(task, node):
                raise FitException(FitError(task=task, node=node,
                                            reasons=[NODE_AFFINITY_FAILED]))
            if not _taints_ok(task, node):
                raise FitException(FitError(task=task, node=node,
                                            reasons=[TAINT_FAILED]))
            if not _ports_ok(task, node):
                raise FitException(FitError(task=task, node=node,
                                            reasons=[NODE_PORT_FAILED]))
            if not _gpu_share_ok(task, node):
                raise FitException(FitError(
                    task=task, node=node,
                    reasons=["node(s) didn't have enough free gpu memory"]))

        ssn.add_predicate_fn(NAME, predicate_fn)

    def _ports_and_gpu_mask(self, ssn):
        def mask_fn(batch, narr, feats):
            mask = np.ones((batch.g_pad, narr.n_pad), bool)
            # only sweep groups that actually use host ports or shared GPUs
            for g, members in enumerate(batch.group_members):
                rep = batch.tasks[members[0]]
                uses_ports = bool(rep.pod.spec.host_ports)
                uses_gpu = rep.resreq.get(GPU_MEMORY_RESOURCE) > 0
                if not (uses_ports or uses_gpu):
                    continue
                for name, i in narr.name_to_idx.items():
                    node = ssn.nodes[name]
                    if uses_ports and not _ports_ok(rep, node):
                        mask[g, i] = False
                    elif uses_gpu and not _gpu_share_ok(rep, node):
                        mask[g, i] = False
            return mask
        return mask_fn


register_plugin_builder(NAME, PredicatesPlugin)
