"""overcommit plugin (reference: pkg/scheduler/plugins/overcommit/
overcommit.go).

Gates enqueue admission on overcommitted cluster headroom: idle =
total x factor - used (default factor 1.2, floor 1.0); a job may enter the
Inqueue phase only while the already-inqueue jobs' MinResources plus its
own fit that headroom. JobEnqueued charges admitted jobs against the
running total (overcommit.go:71-127).
"""

from __future__ import annotations

from ..framework.arguments import Arguments
from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT, REJECT
from ..models.objects import PodGroupPhase
from ..models.resource import Resource, ZERO

NAME = "overcommit"

OVERCOMMIT_FACTOR = "overcommit-factor"
DEFAULT_FACTOR = 1.2


class OvercommitPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = Arguments(arguments or {})
        self.idle = Resource()
        self.inqueue = Resource()
        self.factor = DEFAULT_FACTOR

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        self.factor = self.arguments.get_float(OVERCOMMIT_FACTOR,
                                               DEFAULT_FACTOR)
        if self.factor < 1.0:
            self.factor = DEFAULT_FACTOR

        total, used = Resource(), Resource()
        for node in ssn.nodes.values():
            total.add(node.allocatable)
            used.add(node.used)
        self.idle = total.clone().multi(self.factor)
        # fit_delta-style subtraction: used may exceed total x factor
        for name in used.resource_names():
            self.idle.set(name, self.idle.get(name) - used.get(name))

        self.inqueue = Resource()
        for job in ssn.jobs.values():
            if (job.pod_group.status.phase == PodGroupPhase.INQUEUE
                    and job.pod_group.spec.min_resources is not None):
                self.inqueue.add(job.get_min_resources())

        def enqueueable_fn(job):
            if job.pod_group.spec.min_resources is None:
                return PERMIT
            job_min_req = job.get_min_resources()
            if self.inqueue.clone().add(job_min_req).less_equal(self.idle,
                                                                ZERO):
                return PERMIT
            return REJECT

        ssn.add_job_enqueueable_fn(NAME, enqueueable_fn)

        def enqueued_fn(job):
            if job.pod_group.spec.min_resources is None:
                return
            self.inqueue.add(job.get_min_resources())

        ssn.add_job_enqueued_fn(NAME, enqueued_fn)

    def on_session_close(self, ssn) -> None:
        self.idle = Resource()
        self.inqueue = Resource()


register_plugin_builder(NAME, OvercommitPlugin)
