"""proportion plugin (reference: pkg/scheduler/plugins/proportion/
proportion.go).

Extension points: QueueOrder (by share = dominant allocated/deserved),
Reclaimable (victims only from queues above deserved), Overused,
JobEnqueueable (capability gate), plus allocate/deallocate event handlers
keeping shares live.

TPU-first: the iterative weighted water-fill of per-queue ``deserved``
(proportion.go:129-194) runs as one compiled ``lax.while_loop`` over dense
[Q,R] arrays (ops/fairshare.py::proportion_waterfill); shares use the same
dominant-share kernel as drf.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT, REJECT, EventHandler
from ..metrics import metrics as m
from ..models.arrays import ResourceIndex
from ..models.job_info import TaskStatus
from ..models.objects import PodGroupPhase
from ..models.resource import INFINITY, ZERO, Resource

NAME = "proportion"


def _share(allocated: Resource, deserved: Resource) -> float:
    """max_r allocated_r/deserved_r with 0/0=0, x/0=1 (helpers.go:47-60)."""
    res = 0.0
    for rn in deserved.resource_names():
        d = deserved.get(rn)
        a = allocated.get(rn)
        res = max(res, (0.0 if a == 0 else 1.0) if d == 0 else a / d)
    return res


class _QueueAttr:
    __slots__ = ("queue_id", "name", "weight", "share", "deserved",
                 "allocated", "request", "inqueue", "capability")

    def __init__(self, queue):
        self.queue_id = queue.uid
        self.name = queue.name
        self.weight = queue.weight
        self.share = 0.0
        self.deserved = Resource()
        self.allocated = Resource()
        self.request = Resource()
        self.inqueue = Resource()
        self.capability: Optional[Resource] = None
        if queue.queue.spec.capability:
            self.capability = Resource.from_resource_list(
                queue.queue.spec.capability)


class ProportionPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.queue_opts: Dict[str, _QueueAttr] = {}
        self.total = Resource()

    def name(self) -> str:
        return NAME

    # -- session open ------------------------------------------------------

    def on_session_open(self, ssn) -> None:
        self.total = ssn.total_resource.clone()

        for job in ssn.jobs.values():
            if job.queue not in ssn.queues:
                continue
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                attr = _QueueAttr(ssn.queues[job.queue])
                self.queue_opts[job.queue] = attr
            # allocated-status and pending-request sums are maintained as
            # running aggregates on JobInfo (one add per job instead of
            # one per task — 50k adds per cycle at the burst benchmark)
            attr.allocated.add(job.allocated)
            attr.request.add(job.allocated)
            attr.request.add(job.pending_request)
            if job.pod_group.status.phase == PodGroupPhase.INQUEUE:
                attr.inqueue.add(job.get_min_resources())

        for attr in self.queue_opts.values():
            m.update_queue_allocated(attr.name, attr.allocated.milli_cpu,
                                     attr.allocated.memory)
            m.update_queue_weight(attr.name, attr.weight)

        self._waterfill()

        if ssn.solver is not None:
            def queue_budget_fn(queue_name, rindex):
                """Feed live Overused gating into the allocate kernel: the
                scan stops selecting a queue's jobs once its in-scan
                allocation exceeds deserved (proportion.go:238-250 evaluated
                at job granularity, like the reference's per-pop check)."""
                for attr in self.queue_opts.values():
                    if attr.name == queue_name:
                        return (rindex.vec(attr.allocated),
                                rindex.vec(attr.deserved))
                return None

            ssn.solver.add_queue_budget_fn(queue_budget_fn)

        def queue_order_fn(l, r) -> int:
            ls = self.queue_opts[l.uid].share
            rs = self.queue_opts[r.uid].share
            return 0 if ls == rs else (-1 if ls < rs else 1)

        ssn.add_queue_order_fn(NAME, queue_order_fn)

        def reclaimable_fn(reclaimer, reclaimees):
            """Victims only from queues holding more than deserved
            (proportion.go:211-236)."""
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs.get(reclaimee.job)
                if job is None or job.queue not in self.queue_opts:
                    continue
                attr = self.queue_opts[job.queue]
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less_partly(reclaimer.resreq, ZERO):
                    continue
                if not allocated.less_equal(attr.deserved, ZERO):
                    allocated.sub(reclaimee.resreq)
                    victims.append(reclaimee)
            return victims, PERMIT

        ssn.add_reclaimable_fn(NAME, reclaimable_fn)

        def overused_fn(queue) -> bool:
            attr = self.queue_opts.get(queue.uid)
            if attr is None:
                return False
            overused = not attr.allocated.less_equal(attr.deserved, ZERO)
            m.update_queue_overused(attr.name, overused)
            return overused

        ssn.add_overused_fn(NAME, overused_fn)

        def job_enqueueable_fn(job) -> int:
            """Capability gate: minResources must fit capability minus
            allocated+inqueue (proportion.go:252-276)."""
            queue = ssn.queues.get(job.queue)
            attr = self.queue_opts.get(job.queue)
            if queue is None or attr is None:
                return PERMIT
            if not queue.queue.spec.capability:
                return PERMIT
            if job.pod_group.spec.min_resources is None:
                return PERMIT
            min_req = job.get_min_resources()
            want = min_req.clone().add(attr.allocated).add(attr.inqueue)
            cap = Resource.from_resource_list(queue.queue.spec.capability)
            if want.less_equal(cap, INFINITY):
                attr.inqueue.add(min_req)
                return PERMIT
            return REJECT

        ssn.add_job_enqueueable_fn(NAME, job_enqueueable_fn)

        def _apply_total(job, total, sign):
            """The single queue-share update body (proportion.go events):
            per-task events pass one resreq, batched events a gang's sum."""
            if job is None or job.queue not in self.queue_opts:
                return
            attr = self.queue_opts[job.queue]
            if sign > 0:
                attr.allocated.add(total)
            else:
                attr.allocated.sub(total)
            attr.share = _share(attr.allocated, attr.deserved)
            # queue gauges are last-write-wins: one sweep at session close
            # replaces a pair of gauge updates per placed gang

        ssn.add_event_handler(EventHandler(
            allocate_func=lambda e:
                _apply_total(ssn.jobs.get(e.task.job), e.task.resreq, +1),
            deallocate_func=lambda e:
                _apply_total(ssn.jobs.get(e.task.job), e.task.resreq, -1),
            batch_allocate_func=lambda job, tasks, total:
                _apply_total(job, total, +1),
            batch_deallocate_func=lambda job, tasks, total:
                _apply_total(job, total, -1)))

    # -- the water-fill kernel --------------------------------------------

    def _waterfill(self) -> None:
        """Run the deserved water-fill on the TPU kernel and write results
        back into the per-queue attrs."""
        if not self.queue_opts:
            return
        import jax.numpy as jnp

        from ..ops.fairshare import proportion_waterfill

        attrs = list(self.queue_opts.values())
        rindex = ResourceIndex(
            {rn for a in attrs for rn in a.request.scalars} |
            set(self.total.scalars))
        q = len(attrs)
        weight = np.array([a.weight for a in attrs], np.float32)
        request = np.stack([rindex.vec(a.request) for a in attrs])
        capability = np.full((q, rindex.r), np.inf, np.float32)
        for i, a in enumerate(attrs):
            if a.capability is not None:
                capability[i] = rindex.vec_capability(a.capability)
        total = rindex.vec(self.total)

        deserved, _ = proportion_waterfill(jnp.asarray(weight),
                                           jnp.asarray(capability),
                                           jnp.asarray(request),
                                           jnp.asarray(total))
        deserved = np.asarray(deserved) / rindex.scales  # back to base units
        for i, a in enumerate(attrs):
            a.deserved = Resource(milli_cpu=float(deserved[i, 0]),
                                  memory=float(deserved[i, 1]))
            for name in rindex.names[2:]:
                a.deserved.set_scalar(name, float(deserved[i, rindex.index[name]]))
            a.share = _share(a.allocated, a.deserved)
            m.update_queue_deserved(a.name, a.deserved.milli_cpu,
                                    a.deserved.memory)
            m.update_queue_share(a.name, a.share)

    def on_session_close(self, ssn) -> None:
        for attr in self.queue_opts.values():
            m.update_queue_allocated(attr.name, attr.allocated.milli_cpu,
                                     attr.allocated.memory)
            m.update_queue_share(attr.name, attr.share)
        self.queue_opts = {}
        self.total = Resource()


register_plugin_builder(NAME, ProportionPlugin)
