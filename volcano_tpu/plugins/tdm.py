"""tdm (time-division multiplexing) plugin (reference: pkg/scheduler/
plugins/tdm/tdm.go).

Revocable nodes carry a ``tdm.revocable-zone.<name>`` time window argument
("HH:MM-HH:MM"); inside the window only revocable-zone-annotated tasks may
land there (predicate + max-score node order). Outside the window,
VictimTasks drains preemptable pods from the zone's nodes in
``tdm.evict.period`` steps bounded by the job's disruption budget.
Preemptable jobs order first for placement and cannot themselves preempt.

The predicate/score pair is contributed to the batch solver as a
vectorized [G, N] mask/score (computed from the zone clock host-side),
so the allocate scan and preempt/backfill feasibility see it natively.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import PERMIT, REJECT
from ..models.job_info import TaskStatus, parse_duration

NAME = "tdm"

REVOCABLE_ZONE_PREFIX = "tdm.revocable-zone."
EVICT_PERIOD = "tdm.evict.period"
EVICT_MAX_STEP = "tdm.evict.max-step"
DEFAULT_POD_EVICT_NUM = 1
MAX_NODE_SCORE = 100.0

_last_evict_at = 0.0


def parse_revocable_zone(raw: str) -> Optional[tuple]:
    """"HH:MM-HH:MM" -> (start_min, end_min) minutes-of-day; an end at or
    before the start rolls into the next day (tdm.go:89-117)."""
    parts = str(raw).strip().split("-")
    if len(parts) != 2:
        return None
    try:
        h1, m1 = (int(x) for x in parts[0].split(":"))
        h2, m2 = (int(x) for x in parts[1].split(":"))
    except ValueError:
        return None
    start, end = h1 * 60 + m1, h2 * 60 + m2
    if start >= end:
        end += 24 * 60
    return start, end


class TdmPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.revocable_zone: Dict[str, str] = {}
        self.evict_period = 60.0
        for k, v in self.arguments.items():
            if REVOCABLE_ZONE_PREFIX in str(k):
                self.revocable_zone[str(k).replace(REVOCABLE_ZONE_PREFIX,
                                                   "", 1)] = v
        if EVICT_PERIOD in self.arguments:
            d = parse_duration(self.arguments[EVICT_PERIOD])
            if d is not None:
                self.evict_period = d

    def name(self) -> str:
        return NAME

    # -- zone clock --------------------------------------------------------

    def available_revocable_zone(self, rz: str) -> bool:
        raw = self.revocable_zone.get(rz)
        if raw is None:
            return False
        window = parse_revocable_zone(raw)
        if window is None:
            return False
        start, end = window
        lt = time.localtime()
        now_min = lt.tm_hour * 60 + lt.tm_min
        return start <= now_min <= end or start <= now_min + 24 * 60 <= end

    # -- session hooks -----------------------------------------------------

    def on_session_open(self, ssn) -> None:
        def predicate_fn(task, node):
            """Revocable nodes only admit revocable-zone tasks inside the
            active window (tdm.go:146-167)."""
            if not node.revocable_zone:
                return
            if not self.available_revocable_zone(node.revocable_zone):
                raise RuntimeError(
                    f"plugin {NAME} predicates: current time beyond "
                    f"revocable zone {node.revocable_zone}")
            if not task.revocable_zone:
                raise RuntimeError(
                    f"plugin {NAME} predicates: task {task.namespace}/"
                    f"{task.name} not allowed on revocable node {node.name}")

        ssn.add_predicate_fn(NAME, predicate_fn)

        def node_order_fn(task, node):
            """Max score steers revocable tasks onto active revocable nodes
            (tdm.go:169-190)."""
            if not node.revocable_zone:
                return 0.0
            if not self.available_revocable_zone(node.revocable_zone):
                return 0.0
            if not task.revocable_zone:
                return 0.0
            return MAX_NODE_SCORE

        ssn.add_node_order_fn(NAME, node_order_fn)

        if ssn.solver is not None:
            if ssn.plugin_enabled(NAME, "enabledPredicate"):
                ssn.solver.mark_vectorized(NAME)
                ssn.solver.add_mask_fn(self._solver_mask(ssn))
            if ssn.plugin_enabled(NAME, "enabledNodeOrder"):
                ssn.solver.add_static_score_fn(self._solver_score(ssn))

        def preemptable_fn(preemptor, preemptees):
            """Preemptable / revocable workloads cannot preempt; victims are
            preemptable Running tasks on non-revocable nodes, bounded per
            job by its disruption budget (tdm.go:192-230)."""
            if preemptor.preemptable or preemptor.revocable_zone:
                return [], REJECT
            tasks_by_job: Dict[str, List] = {}
            for task in preemptees:
                if not task.preemptable or task.status != TaskStatus.Running:
                    continue
                node = ssn.nodes.get(task.node_name)
                if node is None or node.revocable_zone:
                    continue
                tasks_by_job.setdefault(task.job, []).append(task)
            victims = []
            for job_uid, tasks in tasks_by_job.items():
                job = ssn.jobs.get(job_uid)
                if job is not None:
                    victims.extend(self._max_victims(job, tasks))
            return victims, PERMIT

        ssn.add_preemptable_fn(NAME, preemptable_fn)

        def victims_fn():
            """Outside the window, drain preemptable pods from the zone's
            nodes once per evict period (tdm.go:232-260)."""
            # wall time on purpose (not ssn.clock): tdm is time-of-day
            # multiplexing — the zone windows above parse against
            # time.localtime() — and _last_evict_at is a module global
            # shared across schedulers in-process, so mixing timebases
            # here would leak virtual stamps into production pacing
            global _last_evict_at
            if _last_evict_at + self.evict_period > time.time():   # lint: allow(clock-discipline): time-of-day multiplexing is wall-clock by design (windows parse against localtime; see comment above)
                return []
            victims = []
            for rz in self.revocable_zone:
                if self.available_revocable_zone(rz):
                    continue
                tasks_by_job: Dict[str, List] = {}
                for node in ssn.revocable_nodes.values():
                    if node.revocable_zone != rz:
                        continue
                    for task in node.tasks.values():
                        if task.preemptable and task.status == TaskStatus.Running:
                            tasks_by_job.setdefault(task.job, []).append(task)
                for job_uid, tasks in tasks_by_job.items():
                    job = ssn.jobs.get(job_uid)
                    if job is not None:
                        victims.extend(self._max_victims(job, tasks))
            _last_evict_at = time.time()   # lint: allow(clock-discipline): wall-clock by design — shared module-global evict pacing, see comment above
            return victims

        ssn.add_victim_tasks_fns(NAME, victims_fn)

        def job_order_fn(l, r):
            """Non-preemptable jobs place first (tdm.go:262-275)."""
            if l.preemptable == r.preemptable:
                return 0
            return -1 if not l.preemptable else 1

        ssn.add_job_order_fn(NAME, job_order_fn)

        def job_pipelined_fn(job):
            occupied = job.waiting_task_num() + job.ready_task_num()
            return PERMIT if occupied >= job.min_available else REJECT

        ssn.add_job_pipelined_fn(NAME, job_pipelined_fn)

        def job_starving_fn(job):
            """Preemptable (elastic) jobs never count as starving; others
            starve while they have pending tasks (tdm.go:287-294)."""
            if job.preemptable:
                return False
            return len(job.task_status_index.get(TaskStatus.Pending, {})) > 0

        ssn.add_job_starving_fns(NAME, job_starving_fn)

    # -- vectorized contributions -----------------------------------------

    def _node_zone_state(self, ssn, narr):
        """Per node: (is_revocable, zone_active) numpy arrays."""
        n_pad = narr.idle.shape[0]
        revocable = np.zeros(n_pad, bool)
        active = np.zeros(n_pad, bool)
        for i, name in enumerate(narr.names):
            node = ssn.nodes.get(name)
            if node is None or not node.revocable_zone:
                continue
            revocable[i] = True
            active[i] = self.available_revocable_zone(node.revocable_zone)
        return revocable, active

    def _solver_mask(self, ssn):
        def mask_fn(batch, narr, feats):
            revocable, active = self._node_zone_state(ssn, narr)
            if not revocable.any():
                return None   # no revocable nodes: nothing to mask
            task_rz = np.zeros(batch.g_pad, bool)
            for g, members in enumerate(batch.group_members):
                task_rz[g] = bool(batch.tasks[members[0]].revocable_zone)
            ok = ~revocable[None, :] | (active[None, :] & task_rz[:, None])
            return ok
        mask_fn.explain_label = "tdm"
        return mask_fn

    def _solver_score(self, ssn):
        def score_fn(batch, narr, feats):
            revocable, active = self._node_zone_state(ssn, narr)
            if not (revocable & active).any():
                return None   # nothing to attract: no [G,N] transfer
            task_rz = np.zeros(batch.g_pad, bool)
            for g, members in enumerate(batch.group_members):
                task_rz[g] = bool(batch.tasks[members[0]].revocable_zone)
            score = (revocable & active)[None, :] & task_rz[:, None]
            return score.astype(np.float32) * MAX_NODE_SCORE
        return score_fn

    # -- disruption budget -------------------------------------------------

    @staticmethod
    def _parse_int_or_percent(value: str, total: int) -> int:
        import math
        v = str(value).strip()
        if v.endswith("%"):
            try:
                return math.ceil(float(v[:-1]) * total / 100.0)
            except ValueError:
                return 0
        try:
            return int(v)
        except ValueError:
            return 0

    def _max_victims(self, job, victims):
        """Clip a job's victim list to its disruption budget
        (tdm.go:305-334)."""
        return victims[:self._get_max_pod_evict_num(job)]

    def _get_max_pod_evict_num(self, job) -> int:
        running = len(job.task_status_index.get(TaskStatus.Running, {}))
        n_tasks = len(job.tasks)
        if job.budget.max_unavailable:
            max_unavailable = self._parse_int_or_percent(
                job.budget.max_unavailable, n_tasks)
            final = (len(job.task_status_index.get(TaskStatus.Succeeded, {}))
                     + len(job.task_status_index.get(TaskStatus.Failed, {})))
            real_unavailable = n_tasks - final - running
            if real_unavailable >= max_unavailable:
                return 0
            return max_unavailable - real_unavailable
        if job.budget.min_available:
            min_available = self._parse_int_or_percent(
                job.budget.min_available, n_tasks)
            if running >= min_available:
                return running - min_available
        return DEFAULT_POD_EVICT_NUM


register_plugin_builder(NAME, TdmPlugin)
