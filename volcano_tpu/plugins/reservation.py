"""reservation plugin (reference: pkg/scheduler/plugins/reservation/
reservation.go).

TargetJob: among pending jobs, the highest priority, ties broken by the
longest wait since scheduling started (reservation.go:44-118). ReservedNodes:
each cycle lock the unlocked node with the most idle resources
(reservation.go:56-65,120-141).
"""

from __future__ import annotations

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..models.resource import ZERO
from ..utils.reservation import RESERVATION

NAME = "reservation"


class ReservationPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return NAME

    def on_session_open(self, ssn) -> None:
        def target_job_fn(jobs):
            if not jobs:
                return None
            highest = max(job.priority for job in jobs)
            candidates = [job for job in jobs if job.priority == highest]
            now = ssn.clock.now()

            def waited(job):
                start = job.scheduling_start_time or now
                return now - start

            return max(candidates, key=waited)

        ssn.add_target_job_fn(NAME, target_job_fn)

        def reserved_nodes_fn():
            max_idle = None
            for node in ssn.nodes.values():
                if node.name in RESERVATION.locked_nodes:
                    continue
                if max_idle is None or max_idle.idle.less_equal(node.idle,
                                                               ZERO):
                    max_idle = node
            if max_idle is not None:
                # only the name is ever consulted; storing the snapshot
                # NodeInfo would pin dead sessions in the process global
                RESERVATION.locked_nodes[max_idle.name] = None

        ssn.add_reserved_nodes_fn(NAME, reserved_nodes_fn)

    def on_session_close(self, ssn) -> None:
        pass


register_plugin_builder(NAME, ReservationPlugin)
