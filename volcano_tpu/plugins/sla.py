"""sla plugin (reference: pkg/scheduler/plugins/sla/sla.go).

Service-level agreement on job waiting time: jobs whose Pending age
exceeds their ``sla-waiting-time`` (per-job annotation, falling back to the
plugin argument) jump the job order and are force-permitted by the
JobEnqueueable and JobPipelined voters (sla.go:103-149).
"""

from __future__ import annotations

from typing import Optional

from ..framework.plugin import Plugin
from ..framework.registry import register_plugin_builder
from ..framework.session import ABSTAIN, PERMIT
from ..models.job_info import parse_duration

NAME = "sla"

JOB_WAITING_TIME = "sla-waiting-time"


class SlaPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.job_waiting_time: Optional[float] = None

    def name(self) -> str:
        return NAME

    def _waiting_time(self, job) -> Optional[float]:
        """Per-job setting wins over the global argument (sla.go:55-64)."""
        if job.waiting_time is not None:
            return job.waiting_time
        return self.job_waiting_time

    def on_session_open(self, ssn) -> None:
        if JOB_WAITING_TIME in self.arguments:
            jwt = parse_duration(self.arguments[JOB_WAITING_TIME])
            if jwt is not None and jwt > 0:
                self.job_waiting_time = jwt

        def job_order_fn(l, r):
            """Jobs with an SLA deadline order by creation + waiting time;
            jobs without one sort last (sla.go:103-130)."""
            ljwt, rjwt = self._waiting_time(l), self._waiting_time(r)
            if ljwt is None:
                return 0 if rjwt is None else 1
            if rjwt is None:
                return -1
            ldeadline = l.creation_timestamp + ljwt
            rdeadline = r.creation_timestamp + rjwt
            if ldeadline < rdeadline:
                return -1
            if ldeadline > rdeadline:
                return 1
            return 0

        ssn.add_job_order_fn(NAME, job_order_fn)

        def permitable_fn(job):
            jwt = self._waiting_time(job)
            if jwt is None:
                return ABSTAIN
            if ssn.clock.now() - job.creation_timestamp < jwt:
                return ABSTAIN
            return PERMIT

        ssn.add_job_enqueueable_fn(NAME, permitable_fn)
        ssn.add_job_pipelined_fn(NAME, permitable_fn)

    def on_session_close(self, ssn) -> None:
        pass


register_plugin_builder(NAME, SlaPlugin)
