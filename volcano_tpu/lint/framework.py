"""Shared visitor framework for the invariant lint suite.

The unit of work is a :class:`ParsedModule` — source text, AST with
parent links, and the per-line pragma index.  Rules receive a
:class:`LintContext` (all parsed modules plus repo-layout anchors) and
return :class:`Finding`s; suppression (pragmas, baseline) is applied by
the runner, never inside a rule, so a rule's raw findings stay visible
to the stale-baseline check.
"""

from __future__ import annotations

import ast
import os
import re
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: pragma grammar: ``# lint: allow(rule-a, rule-b): reason text``.
#: The reason is MANDATORY — an allow without a why is how conventions
#: rot; the runner rejects bare pragmas as findings of their own.
_PRAGMA_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*(?P<rules>[\w\-, ]+?)\s*\)\s*"
    r"(?::\s*(?P<reason>\S.*))?")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative, e.g. volcano_tpu/trace/tracer.py
    line: int            # 1-based; 0 for whole-file/whole-tree findings
    message: str

    def key(self) -> str:
        """Stable baseline identity: rule + path + a crc of the stripped
        source line (line NUMBERS drift on unrelated edits; line CONTENT
        only changes when the violating code itself changes)."""
        return f"{self.rule}|{self.path}|{self.line_crc}"

    @property
    def line_crc(self) -> str:
        # whole-file findings (line 0) have no source line; crc the
        # MESSAGE instead so distinct synthetic findings on the same
        # rule+path never collapse onto one baseline key (one entry
        # must not silently waive every future line-0 finding there)
        text = self._line_text or self.message
        return format(zlib.crc32(text.encode()), "08x")

    # populated by ParsedModule.finding(); empty for synthetic findings
    _line_text: str = field(default="", compare=False)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


class ParsedModule:
    """One Python source file: text, AST (with ``.parent`` links), and
    the pragma index mapping line -> {rule: reason}."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        annotate_parents(self.tree)
        self.pragmas: Dict[int, Dict[str, str]] = {}
        self.bad_pragmas: List[int] = []
        self._index_pragmas()

    def _index_pragmas(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            reason = (m.group("reason") or "").strip()
            if not reason:
                self.bad_pragmas.append(i)
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            entry = self.pragmas.setdefault(i, {})
            for r in rules:
                entry[r] = reason
            # a standalone pragma comment covers the next line too, so
            # multi-line statements can carry the allow above them
            if text.lstrip().startswith("#"):
                nxt = self.pragmas.setdefault(i + 1, {})
                for r in rules:
                    nxt.setdefault(r, reason)

    def allowed(self, rule: str, line: int) -> bool:
        return rule in self.pragmas.get(line, ())

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line) or 0
        return Finding(rule=rule, path=self.relpath, line=line,
                       message=message, _line_text=self.line_text(line))


def annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return a
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` Attribute/Name chain -> ``"a.b.c"`` (None if the root
    isn't a plain Name)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.AST, module: str) -> Set[str]:
    """Local names bound to ``module`` (``import time`` -> {"time"},
    ``import numpy as np`` with module="numpy" -> {"np"}).

    ``import numpy.random`` (no asname) binds the ROOT name — it counts
    for module="numpy", not for module="numpy.random"; with an asname
    the bound name refers to the full dotted module, so
    ``import numpy.random as npr`` counts ONLY for "numpy.random"."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    if a.name == module:
                        out.add(a.asname)
                elif (a.name == module
                      or a.name.startswith(module + ".")) \
                        and a.name.split(".")[0] == module:
                    out.add(module)
    return out


def importfrom_aliases(tree: ast.AST, module_suffix: str,
                       names: Optional[Set[str]] = None) -> Set[str]:
    """Local names bound by ``from <...module_suffix> import X [as Y]``.
    Relative imports match on the suffix (``..metrics`` vs ``metrics``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == module_suffix or mod.endswith("." + module_suffix):
                for a in node.names:
                    if names is None or a.name in names:
                        out.add(a.asname or a.name)
    return out


@dataclass
class LintContext:
    """Everything a rule may look at.

    ``package_root`` is the ``volcano_tpu`` package directory;
    ``tests_dir`` the repo's ``tests/`` directory (may be absent for
    fixture trees); ``native_src`` the fastmodel C source path."""

    package_root: str
    tests_dir: Optional[str]
    modules: List[ParsedModule]
    repo_root: str

    def module(self, relpath: str) -> Optional[ParsedModule]:
        for m in self.modules:
            if m.relpath == relpath or m.relpath.endswith("/" + relpath):
                return m
        return None

    def in_scope(self, mod: ParsedModule,
                 prefixes: Tuple[str, ...]) -> bool:
        """Scope test against the module path RELATIVE to the package
        root (so fixture trees in tmp dirs scope identically)."""
        rel = self.pkg_relpath(mod)
        return rel.startswith(prefixes)

    def pkg_relpath(self, mod: ParsedModule) -> str:
        rel = os.path.relpath(mod.path, self.package_root)
        return rel.replace(os.sep, "/")

    @property
    def native_src(self) -> str:
        return os.path.join(self.package_root, "native", "fastmodel.c")

    def tests_sources(self) -> List[Tuple[str, str]]:
        out = []
        if self.tests_dir and os.path.isdir(self.tests_dir):
            for name in sorted(os.listdir(self.tests_dir)):
                if name.endswith(".py"):
                    p = os.path.join(self.tests_dir, name)
                    try:
                        with open(p, encoding="utf-8") as f:
                            out.append((name, f.read()))
                    except OSError:
                        pass
        return out


class Rule:
    """Base class: ``name`` is the pragma/baseline token, ``check``
    returns raw findings (suppression happens in the runner)."""

    name: str = ""
    description: str = ""

    def check(self, ctx: LintContext) -> List[Finding]:
        raise NotImplementedError


def collect_modules(package_root: str,
                    exclude_prefixes: Tuple[str, ...] = ("lint/",)
                    ) -> List[ParsedModule]:
    """Parse every .py under ``package_root`` except the lint suite
    itself (its fixtures would trip its own rules), sorted for
    deterministic output order."""
    repo_root = os.path.dirname(os.path.abspath(package_root))
    mods: List[ParsedModule] = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel_pkg = os.path.relpath(path, package_root).replace(os.sep, "/")
            if rel_pkg.startswith(exclude_prefixes):
                continue
            relpath = os.path.relpath(path, repo_root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                source = f.read()
            try:
                mods.append(ParsedModule(path, relpath, source))
            except SyntaxError as e:
                raise SyntaxError(f"lint: cannot parse {relpath}: {e}")
    return mods
