"""Lint runner: collect modules, run rules, apply pragmas + baseline."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence, Tuple

from . import baseline as baseline_mod
from .framework import Finding, LintContext, Rule, collect_modules
from .rules import (ClockDisciplineRule, DurabilityRule, JitPurityRule,
                    LockDisciplineRule, NativeFallbackParityRule,
                    SeededRandomnessRule)


def default_rules() -> List[Rule]:
    return [ClockDisciplineRule(), LockDisciplineRule(),
            NativeFallbackParityRule(), SeededRandomnessRule(),
            JitPurityRule(), DurabilityRule()]


def run_lint(package_root: str, tests_dir: Optional[str] = None,
             rules: Optional[Sequence[Rule]] = None,
             baseline_path: Optional[str] = None
             ) -> Tuple[List[Finding], LintContext]:
    """Run ``rules`` over the package; returns the POST-suppression
    findings (pragma'd and baselined ones removed, stale-baseline and
    malformed-pragma findings added)."""
    package_root = os.path.abspath(package_root)
    if tests_dir is None:
        cand = os.path.join(os.path.dirname(package_root), "tests")
        tests_dir = cand if os.path.isdir(cand) else None
    modules = collect_modules(package_root)
    ctx = LintContext(package_root=package_root, tests_dir=tests_dir,
                      modules=modules,
                      repo_root=os.path.dirname(package_root))
    if rules is None:
        rules = default_rules()
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check(ctx))
    # inline pragmas: `# lint: allow(rule): reason` on the finding's
    # line (or a standalone pragma comment directly above it)
    by_path = {m.relpath: m for m in modules}
    unsuppressed = []
    for f in raw:
        mod = by_path.get(f.path)
        if mod is not None and f.line and mod.allowed(f.rule, f.line):
            continue
        unsuppressed.append(f)
    # a pragma without a reason is itself a finding: an allow with no
    # why is how a convention rots
    for mod in modules:
        for line in mod.bad_pragmas:
            unsuppressed.append(mod.finding(
                "malformed-pragma", line,
                "lint pragma without a reason — write "
                "`# lint: allow(rule): <why>`"))
    if baseline_path is None:
        baseline_path = baseline_mod.DEFAULT_BASELINE
    entries = baseline_mod.load(baseline_path)
    # stale detection only sees entries for rules that actually RAN (a
    # --rule subset run computes no findings for the other rules, and
    # their still-valid waivers must not be reported as stale) and is
    # judged against the RAW findings — a pragma'd-but-present
    # violation does not make its baseline entry stale
    run_names = {r.name for r in rules}
    entries = [e for e in entries if e[0] in run_names]
    findings, stale = baseline_mod.apply(unsuppressed, entries,
                                         raw_findings=raw)
    findings.extend(stale)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, ctx


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m volcano_tpu.lint",
        description="Invariant lint suite: statically enforce the "
                    "determinism, lock, native-fallback, randomness "
                    "and jit-purity contracts "
                    "(docs/design/static_analysis.md).")
    parser.add_argument("--root", default=None,
                        help="package root to lint (default: the "
                             "installed volcano_tpu package)")
    parser.add_argument("--rule", action="append", default=None,
                        help="run only this rule (repeatable)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: "
                             "volcano_tpu/lint/baseline.txt)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list rules and exit")
    args = parser.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:24s} {r.description}")
        return 0
    if args.rule:
        known = {r.name: r for r in rules}
        unknown = [n for n in args.rule if n not in known]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}; "
                  f"--list-rules shows the catalog", file=sys.stderr)
            return 2
        rules = [known[n] for n in args.rule]
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    findings, ctx = run_lint(root, rules=rules,
                             baseline_path=args.baseline)
    for f in findings:
        print(f.render())
    n_rules = len(rules)
    n_files = len(ctx.modules)
    if findings:
        print(f"\nlint: {len(findings)} finding(s) "
              f"({n_rules} rules over {n_files} files). Fix it, or "
              f"carry a `# lint: allow(<rule>): <reason>` pragma.",
              file=sys.stderr)
        return 1
    print(f"lint: ok — {n_rules} rules over {n_files} files, "
          f"0 findings")
    return 0
