"""Checked-in finding allowlist with stale-entry detection.

Format, one entry per line (``#`` comments and blanks ignored)::

    <rule> <repo-relative-path> <line-crc8>   # free-form note

The third token is :meth:`Finding.key`'s crc of the STRIPPED violating
source line — line numbers drift on unrelated edits, line content only
changes when the violation itself changes.  Matching is content-based:
a baseline entry suppresses every current finding with the same
(rule, path, crc).

The allowlist only shrinks: an entry whose violation no longer exists
is itself an error (``stale baseline entry``), so fixed code cannot
leave a dangling waiver behind for a future regression to hide under.
The shipped baseline is EMPTY — deliberate violations carry inline
``# lint: allow(rule): reason`` pragmas instead; the baseline exists
for bulk-migration situations where annotating hundreds of legacy
sites inline would drown the diff.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Set, Tuple

from .framework import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.txt")


def load(path: str) -> List[Tuple[str, str, str]]:
    entries: List[Tuple[str, str, str]] = []
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for ln, raw in enumerate(f, start=1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 3:
                raise ValueError(
                    f"{path}:{ln}: malformed baseline entry (want "
                    f"`<rule> <path> <crc>`): {raw.strip()!r}")
            entries.append((parts[0], parts[1], parts[2]))
    return entries


def apply(findings: Iterable[Finding], entries,
          raw_findings: Iterable[Finding] = None
          ) -> Tuple[List[Finding], List[Finding]]:
    """Returns (unsuppressed findings, stale-entry findings).

    Staleness is judged against ``raw_findings`` (pre-pragma) when
    given: a violation that still exists but gained an inline pragma
    must NOT make its baseline entry report "the violation is gone" —
    during a bulk migration the two waiver forms legitimately overlap
    until the baseline is pruned."""
    table: Set[Tuple[str, str, str]] = set(entries)
    remaining: List[Finding] = []
    for f in findings:
        if (f.rule, f.path, f.line_crc) not in table:
            remaining.append(f)
    present = {(f.rule, f.path, f.line_crc)
               for f in (raw_findings if raw_findings is not None
                         else findings)}
    stale = [Finding("stale-baseline", path, 0,
                     f"baseline entry `{rule} {path} {crc}` matches no "
                     f"current finding — the violation is gone, delete "
                     f"the entry (the allowlist only shrinks)")
             for rule, path, crc in entries
             if (rule, path, crc) not in present]
    return remaining, stale
