"""jit-purity: no host side effects lexically inside jitted kernels.

A ``jax.jit`` / ``shard_map`` body runs twice in spirit: once as a
Python trace (where a ``print``, metric bump, ledger stamp or clock
read executes at TRACE time — then never again, silently) and forever
after as compiled XLA (where it doesn't exist at all).  Worse, a value-
dependent host call forces a retrace per shape.  The contract for
``ops/``: kernel bodies are pure array programs; telemetry lives in the
host-side wrappers (``kernel_span`` et al.).

Detection is lexical: functions decorated with ``jit``/``jax.jit``
(including ``partial(jax.jit, ...)``) or passed by name to
``jax.jit(...)`` / ``shard_map(...)`` are kernels; their bodies —
nested defs included — must not call ``print``, any alias of the
metrics or ledger modules, or read ``time.*`` / ``datetime.*`` (ALL of
``time``, including ``perf_counter``: inside a kernel even duration
telemetry is trace-time-only noise).
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..framework import (Finding, LintContext, ParsedModule, Rule,
                         dotted_name, import_aliases, importfrom_aliases)

_DEFAULT_SCOPE = ("ops/",)


def _is_jit_expr(node: ast.AST) -> bool:
    """True for `jit`, `jax.jit`, `shard_map`, `partial(jax.jit, ...)`,
    `functools.partial(jit, ...)` decorator/callee expressions."""
    dn = dotted_name(node)
    if dn in ("jit", "jax.jit", "shard_map",
              "jax.experimental.shard_map.shard_map"):
        return True
    if isinstance(node, ast.Call):
        fdn = dotted_name(node.func)
        if fdn in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
        # shard_map(body, mesh=...)(...) style wrappers
        return _is_jit_expr(node.func)
    return False


class JitPurityRule(Rule):
    name = "jit-purity"
    description = ("no prints, metric bumps, ledger stamps or clock "
                   "reads inside jitted/shard_map kernel bodies in ops/")

    def __init__(self, scope=_DEFAULT_SCOPE):
        self.scope = tuple(scope)

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.modules:
            if not ctx.in_scope(mod, self.scope):
                continue
            out.extend(self._check_module(mod))
        return out

    def _check_module(self, mod: ParsedModule) -> List[Finding]:
        kernels = self._find_kernels(mod)
        if not kernels:
            return []
        time_names = import_aliases(mod.tree, "time") | {"time"}
        dt_names = import_aliases(mod.tree, "datetime") | {"datetime"}
        metric_names = (importfrom_aliases(mod.tree, "metrics")
                        | import_aliases(mod.tree, "metrics"))
        ledger_names = (importfrom_aliases(mod.tree, "trace",
                                           {"ledger"})
                        | importfrom_aliases(mod.tree, "trace.ledger"))
        out: List[Finding] = []
        for fn in kernels:
            for node in ast.walk(fn):
                self._check_node(mod, fn, node, time_names, dt_names,
                                 metric_names, ledger_names, out)
        return out

    def _check_node(self, mod, fn, node, time_names, dt_names,
                    metric_names, ledger_names, out) -> None:
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                out.append(mod.finding(
                    self.name, node,
                    f"print() inside jitted kernel `{fn.name}` — "
                    f"executes at trace time only"))
                return
            root = node.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                if root.id in metric_names:
                    out.append(mod.finding(
                        self.name, node,
                        f"metric call inside jitted kernel `{fn.name}` "
                        f"— no-ops under tracing; bump in the host "
                        f"wrapper"))
                elif root.id in ledger_names:
                    out.append(mod.finding(
                        self.name, node,
                        f"ledger stamp inside jitted kernel "
                        f"`{fn.name}` — no-ops under tracing"))
            return
        if isinstance(node, ast.Attribute):
            dn = dotted_name(node)
            if dn is None:
                return
            parts = dn.split(".")
            if len(parts) >= 2 and (parts[0] in time_names
                                    or parts[0] in dt_names) \
                    and parts[0] not in ("self",):
                out.append(mod.finding(
                    self.name, node,
                    f"clock read `{dn}` inside jitted kernel "
                    f"`{fn.name}` — trace-time constant, not a "
                    f"runtime value"))

    # -- kernel discovery -------------------------------------------------

    def _find_kernels(self, mod: ParsedModule) -> List[ast.FunctionDef]:
        defs_by_name = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                defs_by_name.setdefault(node.name, []).append(node)
        kernels: Set[ast.FunctionDef] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    kernels.add(node)
            elif isinstance(node, ast.Call) \
                    and not isinstance(node.func, ast.Call) \
                    and _is_jit_expr(node.func):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        for d in defs_by_name.get(arg.id, ()):
                            kernels.add(d)
        # drop kernels nested inside other kernels: the outer walk
        # visits them anyway and double-reporting is noise
        nested = {child for k in kernels for child in ast.walk(k)
                  if isinstance(child, ast.FunctionDef)
                  and child is not k and child in kernels}
        return sorted(kernels - nested, key=lambda f: f.lineno)
