"""lock-discipline: ``*_locked`` callees and guarded fields stay under
their owning lock.

The store (`apiserver/store.py`, lock attr ``_lock``) and the scheduler
cache (`cache/cache.py`, lock attr ``mutex``) follow the Go-era
``fooLocked()`` convention: a method named ``*_locked`` asserts nothing
and relies on every caller holding the lock.  That contract is enforced
here by a lexical call-graph walk per class:

- a call ``self.X_locked(...)`` must sit inside a ``with self.<lock>:``
  block or inside another ``*_locked`` method (a nested function starts
  a NEW scope: a closure runs at some later time, so it inherits nothing
  lexically — name it ``*_locked`` if it runs under the lock);
- a mutation of a declared guarded field (assignment / augmented
  assignment / `del` / a known mutating method call rooted at the field)
  must likewise happen under the lock.  ``__init__`` is exempt (no other
  thread can hold a reference yet).

The guarded-field sets are declared per file below — they are the
store's object map / rv counter / journal triple and the cache's
snapshot state, i.e. exactly the fields whose unlocked mutation would be
a real data race, not every attribute.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..framework import Finding, LintContext, ParsedModule, Rule

#: method names that mutate common containers in place
_MUTATORS = {"append", "extend", "update", "pop", "popitem", "clear",
             "setdefault", "add", "remove", "discard", "insert",
             "appendleft", "popleft", "__setitem__"}

#: file (relative to the package root) -> lock attr names + guarded
#: field names. Files absent from the tree are skipped (fixture trees).
_DEFAULT_SCOPES: Dict[str, Dict[str, Set[str]]] = {
    "apiserver/store.py": {
        "locks": {"_lock"},
        "guarded": {"_objects", "_rv", "_journal", "_journal_tail",
                    "_journal_parked"},
    },
    "cache/cache.py": {
        "locks": {"mutex"},
        "guarded": {"_prebuilt", "_incr_snap", "_state_version",
                    "_dirty_structural"},
    },
    "replication/follower.py": {
        "locks": {"_lock"},
        "guarded": {"_epoch", "_applied", "_source_head"},
    },
    "replication/election.py": {
        "locks": {"_lock"},
        "guarded": {"_lease", "_version", "_role", "_follower",
                    "_needs_bootstrap"},
    },
}


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("*_locked methods and guarded-field mutations only "
                   "under `with self.<lock>:` or another *_locked method")

    def __init__(self, scopes: Dict[str, Dict[str, Set[str]]] = None):
        self.scopes = scopes if scopes is not None else _DEFAULT_SCOPES

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.modules:
            rel = ctx.pkg_relpath(mod)
            cfg = self.scopes.get(rel)
            if cfg is None:
                continue
            out.extend(self._check_module(mod, cfg["locks"],
                                          cfg["guarded"]))
        return out

    def _check_module(self, mod: ParsedModule, locks: Set[str],
                      guarded: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._walk_fn(mod, item, locks, guarded, out)
        return out

    # -- lexical walk -----------------------------------------------------

    def _is_lock_attr(self, expr: ast.AST, locks: Set[str]) -> bool:
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and expr.attr in locks)

    def _walk_fn(self, mod, fn, locks, guarded, out) -> None:
        # __init__ is exempt wholesale: fields are born there before any
        # other thread can hold a reference
        locked = fn.name.endswith("_locked") or fn.name == "__init__"
        for stmt in fn.body:
            self._walk(mod, stmt, locks, guarded, locked, out)

    def _walk(self, mod, node, locks, guarded, locked, out):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # new runtime scope: a closure only counts as locked when its
            # NAME carries the contract
            inner = node.name.endswith("_locked")
            for child in node.body:
                self._walk(mod, child, locks, guarded, inner, out)
            return
        if isinstance(node, ast.Lambda):
            self._walk(mod, node.body, locks, guarded, False, out)
            return
        if isinstance(node, ast.With):
            acquires = any(self._is_lock_attr(item.context_expr, locks)
                           for item in node.items)
            for item in node.items:
                self._walk(mod, item.context_expr, locks, guarded,
                           locked, out)
            for child in node.body:
                self._walk(mod, child, locks, guarded,
                           locked or acquires, out)
            return
        self._check_node(mod, node, locks, guarded, locked, out)
        for child in ast.iter_child_nodes(node):
            self._walk(mod, child, locks, guarded, locked, out)

    def _check_node(self, mod, node, locks, guarded, locked, out):
        if locked:
            return
        # self.X_locked(...) call outside any lock scope
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr.endswith("_locked") \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.append(mod.finding(
                self.name, node,
                f"`self.{node.func.attr}()` called without holding the "
                f"lock ({'/'.join(sorted(locks))}); wrap in `with "
                f"self.<lock>:` or rename the caller `*_locked`"))
            return
        # guarded-field mutations
        tgt = None
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                f = self._guarded_root(t, guarded)
                if f:
                    tgt = (f, "assignment")
                    break
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                f = self._guarded_root(t, guarded)
                if f:
                    tgt = (f, "del")
                    break
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            f = self._guarded_root(node.func.value, guarded)
            if f:
                tgt = (f, f".{node.func.attr}()")
        if tgt:
            field_name, how = tgt
            out.append(mod.finding(
                self.name, node,
                f"guarded field `self.{field_name}` mutated ({how}) "
                f"outside `with self.<lock>:` "
                f"({'/'.join(sorted(locks))})"))

    def _guarded_root(self, expr: ast.AST, guarded: Set[str]):
        """Peel Tuple/Starred/Subscript/Attribute wrappers down to a
        ``self.<field>`` root; returns the field name when guarded."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                f = self._guarded_root(el, guarded)
                if f:
                    return f
            return None
        if isinstance(expr, ast.Starred):
            return self._guarded_root(expr.value, guarded)
        while isinstance(expr, (ast.Subscript, ast.Attribute)):
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and expr.attr in guarded:
                return expr.attr
            expr = expr.value
        return None
