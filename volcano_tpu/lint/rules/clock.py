"""clock-discipline: deterministic paths never read the wall clock.

Double-run determinism (sim-smoke, flush-bench, storm-smoke) holds only
because every time-dependent decision reads the injected
:class:`~volcano_tpu.utils.clock.Clock`.  A stray ``time.time()`` /
``time.monotonic()`` / ``datetime.now()`` in the store, cache, sim,
trace, scheduler or serving paths re-couples behavior to the wall clock
and only shows up as a storm-scale fingerprint mismatch much later.

``time.perf_counter`` is deliberately NOT banned here: duration
telemetry (histograms, span timings) never feeds a scheduling decision
or a fingerprint.  Wall-clock-by-design sites (``plugins/tdm.py``'s
revocable windows, daemon-loop pacing) carry inline pragmas with the
why.  ``utils/clock.py`` is the one sanctioned implementation site and
is outside this rule's scope by construction.
"""

from __future__ import annotations

import ast
from typing import List

from ..framework import (Finding, LintContext, ParsedModule, Rule,
                         dotted_name, import_aliases, importfrom_aliases)

#: attribute paths (relative to the imported module) that read the wall
#: clock; referencing one is as bad as calling it (it gets passed around
#: as a now_fn)
_TIME_ATTRS = {"time", "monotonic", "monotonic_ns", "time_ns"}
_DATETIME_ATTRS = {"now", "utcnow", "today", "fromtimestamp"}

_DEFAULT_SCOPE = ("apiserver/", "cache/", "sim/", "trace/", "serving/",
                  "plugins/", "replication/", "scheduler.py")


class ClockDisciplineRule(Rule):
    name = "clock-discipline"
    description = ("no time.time()/time.monotonic()/datetime.now() in "
                   "deterministic paths; read the injected Clock seam")

    def __init__(self, scope=_DEFAULT_SCOPE):
        self.scope = tuple(scope)

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.modules:
            if not ctx.in_scope(mod, self.scope):
                continue
            out.extend(self._check_module(mod))
        return out

    def _check_module(self, mod: ParsedModule) -> List[Finding]:
        out: List[Finding] = []
        time_names = import_aliases(mod.tree, "time")
        dt_mod_names = import_aliases(mod.tree, "datetime")
        dt_cls_names = importfrom_aliases(mod.tree, "datetime",
                                          {"datetime", "date"})
        # `from time import time/monotonic` is a violation at the import
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in _TIME_ATTRS:
                        out.append(mod.finding(
                            self.name, node,
                            f"wall-clock import `from time import "
                            f"{a.name}`; use the injected Clock"))
            if not isinstance(node, ast.Attribute):
                continue
            dn = dotted_name(node)
            if dn is None:
                continue
            parts = dn.split(".")
            root, attr = parts[0], parts[-1]
            bad = False
            if root in time_names and len(parts) == 2 \
                    and attr in _TIME_ATTRS:
                bad = True
            elif root in dt_cls_names and len(parts) == 2 \
                    and attr in _DATETIME_ATTRS:
                bad = True
            elif root in dt_mod_names and len(parts) == 3 \
                    and parts[1] in ("datetime", "date") \
                    and attr in _DATETIME_ATTRS:
                bad = True
            if bad:
                out.append(mod.finding(
                    self.name, node,
                    f"wall-clock read `{dn}`; deterministic paths must "
                    f"read the injected Clock (utils/clock.py)"))
        return out
