"""durability: apiserver state writes go through the WAL/atomic-rename
helpers, never raw file I/O.

The crash-consistency contract (docs/design/durability.md) holds only
because every durable mutation funnels through exactly two write
paths: the segmented WAL's framed append (``apiserver/wal.py``) and
the snapshot's fsync + ``os.replace`` tmp-rename
(``persistence.save_store_anchored``). A stray ``open(path, "w")`` or
bare ``os.replace`` in the apiserver package is a state write outside
the protocol — it can tear on power loss, skip the directory fsync,
or bypass the read-only degradation gate — and it only shows up as a
corrupt store after the one crash that matters.

Flagged inside ``apiserver/``:

* ``open(..., "w"/"a"/"wb"/"ab"/...)`` — any write/append mode, and
  ``os.fdopen`` in a write mode (the snapshot helper's own fdopen
  carries the sanctioned pragma);
* ``os.replace`` / ``os.rename`` — atomic installs belong in the one
  helper that fsyncs file and directory.

Read-mode opens are untouched. The sanctioned implementation sites
carry ``# lint: allow(durability): <why>`` pragmas — the escape hatch
IS the audit trail.
"""

from __future__ import annotations

import ast
from typing import List

from ..framework import (Finding, LintContext, ParsedModule, Rule,
                         dotted_name)

_DEFAULT_SCOPE = ("apiserver/",)

#: mode strings whose presence makes an open() a state write
_WRITE_MODE_CHARS = ("w", "a", "x", "+")


def _mode_of(call: ast.Call) -> str:
    """The literal mode argument of an open()/fdopen() call, or "" when
    absent/dynamic (dynamic modes are flagged conservatively)."""
    args = call.args
    if len(args) >= 2:
        node = args[1]
    else:
        node = next((kw.value for kw in call.keywords
                     if kw.arg == "mode"), None)
    if node is None:
        return "r"                       # open() default: read
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return "?"                           # dynamic: treat as a write


def _is_write_mode(mode: str) -> bool:
    return mode == "?" or any(c in mode for c in _WRITE_MODE_CHARS)


class DurabilityRule(Rule):
    name = "durability"
    description = ("apiserver state writes go through the WAL append / "
                   "atomic-rename helpers (open-for-write, os.replace "
                   "and os.rename are flagged outside them)")

    def __init__(self, scope=_DEFAULT_SCOPE):
        self.scope = tuple(scope)

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.modules:
            if not ctx.in_scope(mod, self.scope):
                continue
            out.extend(self._check_module(mod))
        return out

    def _check_module(self, mod: ParsedModule) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # bare open(...) (the builtin; a shadowing local would be
            # stranger than a false positive)
            if isinstance(fn, ast.Name) and fn.id == "open":
                mode = _mode_of(node)
                if _is_write_mode(mode):
                    out.append(mod.finding(
                        self.name, node,
                        f"open(..., {mode!r}) writes apiserver state "
                        f"outside the WAL/atomic-rename helpers; route "
                        f"through WriteAheadLog or "
                        f"save_store_anchored, or pragma the sanctioned "
                        f"helper"))
                continue
            dn = dotted_name(fn)
            if dn is None:
                continue
            if dn in ("os.replace", "os.rename"):
                out.append(mod.finding(
                    self.name, node,
                    f"{dn} outside save_store_anchored: atomic "
                    f"installs must fsync the file before and the "
                    f"directory after the rename — use the snapshot "
                    f"helper or pragma the sanctioned site"))
            elif dn == "os.fdopen" and _is_write_mode(_mode_of(node)):
                out.append(mod.finding(
                    self.name, node,
                    "os.fdopen in a write mode writes apiserver state "
                    "outside the WAL/atomic-rename helpers; use the "
                    "snapshot helper or pragma the sanctioned site"))
        return out
