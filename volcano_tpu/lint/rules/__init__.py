"""Rule catalog for the invariant lint suite (one module per rule)."""

from .clock import ClockDisciplineRule  # noqa: F401
from .durability import DurabilityRule  # noqa: F401
from .locks import LockDisciplineRule  # noqa: F401
from .native_parity import NativeFallbackParityRule  # noqa: F401
from .randomness import SeededRandomnessRule  # noqa: F401
from .jit_purity import JitPurityRule  # noqa: F401
