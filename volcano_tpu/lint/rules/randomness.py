"""seeded-randomness: no process-global RNG in sim/ops/framework.

The sim's double-run gates (sim-smoke, chaos, failover, storm) and the
kernel fuzz suites are only meaningful because every random draw flows
from a seed the run controls: ``random.Random(seed)`` instances,
seed-derived crc32 coins, or jax PRNG keys.  A bare ``random.random()``
or ``np.random.shuffle()`` reads the PROCESS-global generator — shared
mutable state whose sequence depends on import order and on every other
caller, i.e. exactly the non-reproducibility the gates exist to rule
out.

Allowed: constructing generators (``random.Random(seed)``,
``np.random.default_rng(seed)``, ``SeedSequence``/bit-generator
classes) and anything not rooted at the global modules.  Flagged even
when merely referenced (passing ``random.shuffle`` around is the same
leak).
"""

from __future__ import annotations

import ast
from typing import List

from ..framework import (Finding, LintContext, ParsedModule, Rule,
                         dotted_name, import_aliases,
                         importfrom_aliases)

_DEFAULT_SCOPE = ("sim/", "ops/", "framework/",
                  "replication/chaos.py", "replication/election.py")

#: attributes of the `random` module that do NOT touch the global RNG
_RANDOM_OK = {"Random", "SystemRandom"}
#: np.random attributes that construct explicit generators
_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence", "PCG64",
                 "Philox", "MT19937", "BitGenerator", "RandomState"}


class SeededRandomnessRule(Rule):
    name = "seeded-randomness"
    description = ("no bare random.* / np.random.* global-RNG use in "
                   "sim/, ops/, framework/ — seeded generators only")

    def __init__(self, scope=_DEFAULT_SCOPE):
        self.scope = tuple(scope)

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.modules:
            if not ctx.in_scope(mod, self.scope):
                continue
            out.extend(self._check_module(mod))
        return out

    def _check_module(self, mod: ParsedModule) -> List[Finding]:
        out: List[Finding] = []
        random_names = import_aliases(mod.tree, "random")
        numpy_names = import_aliases(mod.tree, "numpy")
        # names bound DIRECTLY to the numpy.random module:
        # `import numpy.random as npr`, `from numpy import random as nr`
        np_random_names = (import_aliases(mod.tree, "numpy.random")
                           | importfrom_aliases(mod.tree, "numpy",
                                                {"random"}))
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "random":
                for a in node.names:
                    if a.name not in _RANDOM_OK:
                        out.append(mod.finding(
                            self.name, node,
                            f"`from random import {a.name}` binds the "
                            f"process-global RNG; construct a seeded "
                            f"random.Random instead"))
                continue
            if not isinstance(node, ast.Attribute):
                continue
            dn = dotted_name(node)
            if dn is None:
                continue
            parts = dn.split(".")
            # resolve the np.random attribute, through either spelling:
            # `np.random.X` (alias of numpy) or `npr.X` (alias of
            # numpy.random itself)
            np_attr = None
            if parts[0] in numpy_names and len(parts) == 3 \
                    and parts[1] == "random":
                np_attr = parts[2]
            elif parts[0] in np_random_names and len(parts) == 2:
                np_attr = parts[1]
            if parts[0] in random_names and len(parts) == 2 \
                    and parts[1] not in _RANDOM_OK:
                out.append(mod.finding(
                    self.name, node,
                    f"global-RNG use `{dn}`; draw from a seeded "
                    f"random.Random"))
            elif np_attr is not None and np_attr not in _NP_RANDOM_OK:
                out.append(mod.finding(
                    self.name, node,
                    f"global-RNG use `{dn}`; use "
                    f"np.random.default_rng(seed)"))
            elif np_attr == "default_rng" \
                    and isinstance(getattr(node, "parent", None), ast.Call) \
                    and node.parent.func is node \
                    and not node.parent.args and not node.parent.keywords:
                out.append(mod.finding(
                    self.name, node,
                    "`np.random.default_rng()` without a seed is "
                    "OS-entropy-seeded; pass an explicit seed"))
        return out
