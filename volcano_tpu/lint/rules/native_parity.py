"""native-fallback-parity: every exported C entry keeps its Python twin.

``native/fastmodel.c`` exports its entries through one ``PyMethodDef``
table.  The contract since PR 8: the native module is an ACCELERATION,
never a semantic fork — every entry has (a) a Python-side call site
wrapped in a fallback path (a ``try/except`` or an
``is not None``/``hasattr``/switch guard, so a missing toolchain or a
native failure degrades to the bit-identical Python body), and (b) at
least one parity test in ``tests/`` that names the entry, so the twin
implementations cannot drift silently.

This rule parses the method table straight out of the C source (no
compiled module needed — the lint gate must run on toolchain-less
boxes) and audits both halves of the contract per entry.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set

from ..framework import Finding, LintContext, Rule, ancestors

_TABLE_RE = re.compile(
    r"static\s+PyMethodDef\s+\w+\[\]\s*=\s*\{(?P<body>.*?)\};",
    re.S)
_ENTRY_RE = re.compile(r'\{\s*"(?P<name>\w+)"\s*,')
#: C-side pragma: `lint: allow(native-fallback-parity, <entry>): reason`
#: anywhere in a comment of fastmodel.c waives BOTH halves of the
#: contract for that entry (test-seam exports exercised directly by
#: tests rather than wired behind a package fallback).
_C_PRAGMA_RE = re.compile(
    r"lint:\s*allow\(\s*native-fallback-parity\s*,\s*(?P<name>\w+)\s*\)"
    r"\s*:\s*(?P<reason>\S)")

#: substrings in a guard test that mark the native path as optional
_GUARD_MARKERS = ("is not None", "hasattr", "NATIVE", "is None")


def exported_entries(c_source: str) -> List[str]:
    m = _TABLE_RE.search(c_source)
    if not m:
        return []
    return _ENTRY_RE.findall(m.group("body"))


class NativeFallbackParityRule(Rule):
    name = "native-fallback-parity"
    description = ("every fastmodel.c exported entry has a guarded "
                   "Python call site and a parity test naming it")

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        src_path = ctx.native_src
        if not os.path.exists(src_path):
            return out    # fixture trees without a native dir
        with open(src_path, encoding="utf-8") as f:
            c_source = f.read()
        all_entries = exported_entries(c_source)
        if not all_entries:
            out.append(Finding(self.name,
                               os.path.relpath(src_path, ctx.repo_root),
                               0, "no PyMethodDef table found"))
            return out
        allowed = {m.group("name")
                   for m in _C_PRAGMA_RE.finditer(c_source)}
        entries = [e for e in all_entries if e not in allowed]
        calls = self._call_sites(ctx, set(entries))
        tests = ctx.tests_sources()
        c_rel = os.path.relpath(src_path, ctx.repo_root).replace(
            os.sep, "/")
        for name in entries:
            sites = calls.get(name, [])
            if not sites:
                out.append(Finding(
                    self.name, c_rel, 0,
                    f"native entry `{name}` has no Python call site — "
                    f"dead export or a fallback that was never wired"))
            elif not any(guarded for _, _, guarded in sites):
                mod, node, _ = sites[0]
                out.append(mod.finding(
                    self.name, node,
                    f"native entry `{name}` is called without a "
                    f"fallback guard (no enclosing try/except or "
                    f"`is not None`/`hasattr`/NATIVE-switch test)"))
            if not any(re.search(rf"\b{name}\b", src)
                       for _, src in tests):
                out.append(Finding(
                    self.name, c_rel, 0,
                    f"native entry `{name}` has no parity test naming "
                    f"it in tests/"))
        return out

    # -- call-site discovery ----------------------------------------------

    def _call_sites(self, ctx: LintContext, names: Set[str]
                    ) -> Dict[str, list]:
        sites: Dict[str, list] = {}
        for mod in ctx.modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in names):
                    continue
                # `self.x(...)` is a method, not the native module
                if isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self":
                    continue
                sites.setdefault(node.func.attr, []).append(
                    (mod, node, self._is_guarded(mod, node)))
        return sites

    def _is_guarded(self, mod, call: ast.Call) -> bool:
        """A call site counts as fallback-wrapped when an enclosing
        try/except exists or an enclosing If's test carries a
        native-availability marker.  The walk crosses nested-function
        boundaries deliberately: a closure DEFINED under
        ``if fm is not None:`` only exists when the native module does
        (the store's ``batch_shard`` idiom), which is as much a fallback
        guard as a try around the call."""
        for a in ancestors(call):
            if isinstance(a, (ast.ClassDef, ast.Module)):
                break
            if isinstance(a, ast.Try) and a.handlers:
                return True
            if isinstance(a, (ast.If, ast.IfExp)):
                test_src = ast.unparse(a.test)
                if any(m in test_src for m in _GUARD_MARKERS):
                    return True
        return False
