"""Project-specific static analysis: the invariant lint suite.

Every perf/robustness layer in this tree leans on a handful of contracts
that are cheap to state and expensive to re-verify at runtime:

- **clock discipline** — deterministic paths read time through the
  injected :class:`~volcano_tpu.utils.clock.Clock` seam, never the wall
  clock directly (a stray ``time.time()`` is a latent double-run
  determinism bug that only a storm-scale smoke gate would catch);
- **lock discipline** — ``*_locked`` methods in the store and cache run
  only under their owning lock, and the declared guarded fields are
  mutated only under it;
- **native-fallback parity** — every C entry exported by
  ``native/fastmodel.c`` has a guarded Python call site (a fallback path
  exists) and a parity test naming it in ``tests/``;
- **seeded randomness** — sim/ops/framework draw randomness from seeded
  generators only, never the process-global RNG;
- **jit purity** — jitted / ``shard_map``-ped kernel bodies in ``ops/``
  contain no metric bumps, ledger stamps, prints or clock reads (they
  silently no-op under tracing or force recompiles).

``python -m volcano_tpu.lint`` runs all rules over the package and exits
nonzero on any finding.  Deliberate violations carry an inline pragma
with a reason::

    x = time.time()   # lint: allow(clock-discipline): export metadata only

or live in the checked-in baseline file
(``volcano_tpu/lint/baseline.txt``); a baseline entry whose violation no
longer exists fails the run, so the allowlist only ever shrinks.
See docs/design/static_analysis.md.
"""

from .framework import Finding, LintContext, Rule, collect_modules  # noqa: F401
from .runner import default_rules, run_lint  # noqa: F401
