"""volcano-tpu: a TPU-native batch scheduling framework.

A ground-up rebuild of the capabilities of Volcano (the CNCF Kubernetes batch
system: gang scheduling, fair-share queues with DRF/proportion, preemption and
reclaim, bin-packing and topology-aware placement, job lifecycle control,
admission, CLI) designed TPU-first: every scheduling cycle snapshots cluster
state into dense structure-of-arrays and evaluates predicates, scoring,
fair-share water-filling and victim selection for all task x node pairs at
once as jitted JAX/XLA kernels.

Layout:
  models/      -- the data model (Resource vectors, Task/Job/Node/Queue infos,
                  CRD-equivalent objects) and the dense snapshot encoding
  ops/         -- the TPU kernels (fit, score, allocate scan, fair share,
                  victim selection, topology)
  framework/   -- Session / Statement / plugin & action registries / conf
  actions/     -- enqueue, allocate, preempt, reclaim, backfill, elect, reserve
  plugins/     -- gang, drf, proportion, predicates, nodeorder, binpack,
                  priority, conformance, overcommit, sla, tdm, task-topology,
                  numaaware, reservation
  cache/       -- informer-fed cluster cache, event handlers, binder/evictor
  apiserver/   -- in-process object store + watch bus (the standalone
                  replacement for the Kubernetes API server)
  controllers/ -- job / queue / podgroup / garbage-collector controllers
  webhooks/    -- admission validate/mutate
  cli/         -- vcctl and single-verb tools
  parallel/    -- device mesh + node-axis sharded solver (shard_map)
  utils/       -- filewatcher, priority queue, test fakes
"""

__version__ = "0.1.0"
