/* fastmodel: C accelerators for the snapshot hot path.
 *
 * The per-cycle Snapshot clones every TaskInfo (50k at the north-star
 * scale); TaskInfo.clone is a verbatim slot copy (all fields shared by
 * reference — see models/job_info.py TaskInfo.clone), which in C is a
 * fixed set of pointer copies + increfs instead of ~18 interpreted
 * attribute assignments.  clone_task_table() clones a whole job's task
 * dict and builds the status index in one pass (the reference pays the
 * same via deepcopy-gen, cache.go:827-876).
 *
 * The slot offsets are read from the class's member descriptors at
 * registration time, so the layout always matches the Python definition.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#define MAX_SLOTS 64

static PyTypeObject *task_type = NULL;
static Py_ssize_t task_offsets[MAX_SLOTS];
static int n_task_slots = -1;
static Py_ssize_t status_offset = -1;
static Py_ssize_t uid_offset = -1;

static int
collect_offsets(PyTypeObject *tp, Py_ssize_t *offsets, int *count,
                Py_ssize_t *status_off, Py_ssize_t *uid_off)
{
    PyObject *slots = PyObject_GetAttrString((PyObject *)tp, "__slots__");
    if (slots == NULL)
        return -1;
    PyObject *seq = PySequence_Fast(slots, "__slots__ not a sequence");
    Py_DECREF(slots);
    if (seq == NULL)
        return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (n > MAX_SLOTS) {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "too many slots");
        return -1;
    }
    *count = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *name = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *descr = PyObject_GetAttr((PyObject *)tp, name);
        if (descr == NULL) {
            Py_DECREF(seq);
            return -1;
        }
        if (Py_TYPE(descr) != &PyMemberDescr_Type) {
            Py_DECREF(descr);
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError,
                            "slot attr is not a member descriptor");
            return -1;
        }
        PyMemberDef *m = ((PyMemberDescrObject *)descr)->d_member;
        offsets[(*count)++] = m->offset;
        const char *cname = PyUnicode_AsUTF8(name);
        if (cname != NULL) {
            if (strcmp(cname, "status") == 0)
                *status_off = m->offset;
            else if (strcmp(cname, "uid") == 0)
                *uid_off = m->offset;
        }
        Py_DECREF(descr);
    }
    Py_DECREF(seq);
    return 0;
}

static PyObject *
register_task_type(PyObject *self, PyObject *arg)
{
    if (!PyType_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a type");
        return NULL;
    }
    PyTypeObject *tp = (PyTypeObject *)arg;
    if (collect_offsets(tp, task_offsets, &n_task_slots,
                        &status_offset, &uid_offset) < 0)
        return NULL;
    if (status_offset < 0 || uid_offset < 0) {
        PyErr_SetString(PyExc_ValueError, "type lacks status/uid slots");
        return NULL;
    }
    Py_XDECREF((PyObject *)task_type);
    Py_INCREF(arg);
    task_type = tp;
    Py_RETURN_NONE;
}

static inline PyObject *
clone_one(PyObject *src)
{
    PyObject *dst = task_type->tp_alloc(task_type, 0);
    if (dst == NULL)
        return NULL;
    char *s = (char *)src, *d = (char *)dst;
    for (int i = 0; i < n_task_slots; i++) {
        PyObject *v = *(PyObject **)(s + task_offsets[i]);
        Py_XINCREF(v);
        *(PyObject **)(d + task_offsets[i]) = v;
    }
    return dst;
}

static PyObject *
clone_task(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0 || Py_TYPE(arg) != task_type) {
        PyErr_SetString(PyExc_TypeError, "not a registered TaskInfo");
        return NULL;
    }
    return clone_one(arg);
}

/* clone_task_table(tasks: dict[uid, TaskInfo])
 *    -> (new_tasks: dict, index: dict[status, dict[uid, TaskInfo]])
 * Exact tasks must be the registered type (callers guarantee it). */
static PyObject *
clone_task_table(PyObject *self, PyObject *arg)
{
    if (n_task_slots < 0) {
        PyErr_SetString(PyExc_RuntimeError, "task type not registered");
        return NULL;
    }
    if (!PyDict_CheckExact(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a dict");
        return NULL;
    }
    PyObject *new_tasks = PyDict_New();
    PyObject *index = PyDict_New();
    if (new_tasks == NULL || index == NULL)
        goto fail;
    Py_ssize_t pos = 0;
    PyObject *key, *value;
    while (PyDict_Next(arg, &pos, &key, &value)) {
        if (Py_TYPE(value) != task_type) {
            PyErr_SetString(PyExc_TypeError, "mixed task types");
            goto fail;
        }
        PyObject *c = clone_one(value);
        if (c == NULL)
            goto fail;
        if (PyDict_SetItem(new_tasks, key, c) < 0) {
            Py_DECREF(c);
            goto fail;
        }
        PyObject *status = *(PyObject **)((char *)c + status_offset);
        PyObject *bucket = PyDict_GetItemWithError(index, status);
        if (bucket == NULL) {
            if (PyErr_Occurred()) {
                Py_DECREF(c);
                goto fail;
            }
            bucket = PyDict_New();
            if (bucket == NULL || PyDict_SetItem(index, status, bucket) < 0) {
                Py_XDECREF(bucket);
                Py_DECREF(c);
                goto fail;
            }
            Py_DECREF(bucket);  /* index holds it */
        }
        if (PyDict_SetItem(bucket, key, c) < 0) {
            Py_DECREF(c);
            goto fail;
        }
        Py_DECREF(c);
    }
    return Py_BuildValue("(NN)", new_tasks, index);
fail:
    Py_XDECREF(new_tasks);
    Py_XDECREF(index);
    return NULL;
}

static PyMethodDef methods[] = {
    {"register_task_type", register_task_type, METH_O,
     "Register the TaskInfo class (reads slot offsets)."},
    {"clone_task", clone_task, METH_O, "Verbatim slot-copy clone."},
    {"clone_task_table", clone_task_table, METH_O,
     "Clone a job's task dict and build the status index."},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastmodel",
    "C accelerators for snapshot cloning.", -1, methods
};

PyMODINIT_FUNC
PyInit_fastmodel(void)
{
    return PyModule_Create(&moduledef);
}
